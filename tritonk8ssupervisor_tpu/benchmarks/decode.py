"""Autoregressive decode throughput benchmark (tokens/sec, ms/token).

The serving-side companion to the training benchmarks: measures
KV-cache generation (models/decode.py) for the GPT-2-small-class LM the
training benchmark uses, so the same checkpoint's serving behavior has
a regression-guarded number next to its training throughput.

Measurement discipline: `generate` is one jitted dispatch (prefill +
a lax.scan over decode steps), so the fence is a device fetch of the
generated tokens; `repeats` independent timed calls give a min/median
spread. Decode is bandwidth-bound (every step re-reads the KV cache and
the weights), so tokens/sec scales with batch until the cache read
saturates HBM — the batch sweep below is the interesting axis.
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import jax
import jax.numpy as jnp

from tritonk8ssupervisor_tpu.models import TransformerLM
from tritonk8ssupervisor_tpu.models import decode as dec
from tritonk8ssupervisor_tpu.parallel import batch_sharding, make_workload_mesh
from tritonk8ssupervisor_tpu.parallel import mesh as mesh_lib
from tritonk8ssupervisor_tpu.parallel.mesh import replicated


def run_benchmark(
    vocab_size: int = 32768,
    num_layers: int = 12,
    num_heads: int = 12,
    embed_dim: int = 768,
    prompt_len: int = 128,
    new_tokens: int = 512,
    batch: int = 8,
    temperature: float = 0.0,
    repeats: int = 3,
    int8: bool = False,
    cache_int8: bool = False,
    unroll: int = 1,
) -> dict:
    max_len = prompt_len + new_tokens
    model = TransformerLM(
        vocab_size=vocab_size,
        num_layers=num_layers,
        num_heads=num_heads,
        embed_dim=embed_dim,
        max_seq_len=max_len,
    )
    # data-parallel decode over every chip the process set sees:
    # params replicate, the batch (and with it the KV cache, by
    # propagation) shards over the mesh's batch axes — so a slice-wide
    # Job measures the slice, not chip 0 with the rest idle
    mesh = make_workload_mesh()
    num_chips = int(mesh.devices.size)
    if batch % mesh_lib.batch_degree(mesh):
        raise ValueError(
            f"--batch {batch} must be divisible by the {num_chips}-chip "
            "data-parallel degree (each chip decodes batch/chips streams)"
        )
    prompt = jax.device_put(
        jax.random.randint(
            jax.random.key(0), (batch, prompt_len), 0, vocab_size
        ),
        batch_sharding(mesh, 2),
    )
    params = model.init(jax.random.key(1), prompt, train=False)["params"]
    if int8:
        # weight-only int8 (models/decode.quantize_params_int8): halves
        # the per-token weight read — the dominant traffic at small batch
        params = dec.quantize_params_int8(params)
    params = jax.device_put(params, replicated(mesh))

    fn = jax.jit(
        functools.partial(
            dec.generate,
            model,
            max_new_tokens=new_tokens,
            temperature=temperature,
            max_len=max_len,
            cache_int8=cache_int8,
            unroll=unroll,
        )
    )
    rng = jax.random.key(2)

    def timed_call():
        # fence with a HOST FETCH of the generated tokens, not
        # block_until_ready: through the tunneled backend the latter can
        # return before execution completes (the same reason
        # utils/perf.timed_windows fences on a loss fetch) — a fetch
        # cannot lie about whether the tokens exist
        start = time.monotonic()
        out = fn(params, prompt=prompt, rng=rng)
        out = jax.device_get(out)
        elapsed = time.monotonic() - start
        assert out.shape == (batch, new_tokens)
        return elapsed

    compile_seconds = timed_call()
    times = sorted(timed_call() for _ in range(repeats))
    median = times[len(times) // 2]
    total_tokens = batch * new_tokens
    return {
        "model": "transformer_lm_decode",
        "platform": jax.default_backend(),
        "num_chips": num_chips,
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "temperature": temperature,
        "int8": bool(int8),
        "cache_int8": bool(cache_int8),
        "unroll": unroll,
        "decode_tokens_per_sec": total_tokens / median,
        "decode_tokens_per_sec_per_chip": total_tokens / median / num_chips,
        # canonical serving vocabulary, shared with the gateway bench
        # (bench_provision.py --serve / BENCH_serve.json) and the lm
        # training bench: one metric name means one thing everywhere,
        # so "decode bench says X tok/s/chip, gateway sustains Y under
        # load" is a comparison, not a conversion
        "tokens_per_sec": total_tokens / median,
        "tokens_per_sec_per_chip": total_tokens / median / num_chips,
        "ms_per_token_per_stream": median / new_tokens * 1000,
        "seconds_median": median,
        "seconds_min": times[0],
        "compile_seconds": compile_seconds,
    }


def run_engine_benchmark(
    vocab_size: int = 512,
    num_layers: int = 4,
    num_heads: int = 4,
    embed_dim: int = 128,
    max_len: int = 512,
    prompt_len: int = 256,
    shared_prefix_len: int = 192,
    new_tokens: int = 32,
    requests: int = 8,
    slots: int = 4,
    page_size: int = 16,
    prefill_chunk: int = 64,
    cache_int8: bool = False,
    spec_k: int = 4,
    spec_new_tokens: int = 96,
    draft_layers: int = 1,
    draft_heads: int = 2,
    draft_embed_dim: int = 32,
    bias_scale: float = 32.0,
) -> dict:
    """The decode-level engine-hot-path A/B (BENCH_engine.json): the
    REAL `serving/engine.SlotEngine` (paged KV + prefix store) driven
    through its variants on this process's devices, reported as a
    machine-readable `modes` list (one entry per engine variant, so
    new variants APPEND instead of overwriting each other's fields):

    - `cold` / `warm` — the PR-11 prefix-reuse pair: the same
      shared-system-prompt stream with the prefix cache off vs on.
      Warm must produce EXACTLY the cold tokens while re-prefilling
      ~0 of the shared prefix.
    - `spec_base` / `spec` — the speculative-decoding pair: a
      decode-heavy stream (`spec_new_tokens` per request, prefix cache
      on both sides, matched KV memory) served without vs with a
      drafter proposing `spec_k` tokens per round. Greedy acceptance
      is exact, so `spec` must be token-identical to `spec_base`; the
      headline `spec_over_baseline` is the tokens/sec/chip ratio.

    Both models share a strong lm_head bias (`bias_scale`) — the
    HIGH-ACCEPTANCE synthetic regime: drafter and target argmax agree
    almost always, so the measured speedup is the engine-mechanics
    ceiling `(k * acceptance + 1) / (k * draft_cost + verify_cost)`,
    not a claim about any particular drafter's quality (acceptance on
    real checkpoints is a property of drafter training; the engine is
    exact at EVERY acceptance rate, pinned in tests/test_spec.py).

    The warmup request (per engine) pays compilation AND seeds the
    warm engine's store, so the timed window measures the steady
    state. Speedup is measured, not assumed — `tokens_per_sec_per_chip`
    here speaks the same canonical vocabulary as BENCH_serve.json and
    the gateway report."""
    import numpy as np

    from tritonk8ssupervisor_tpu.serving.engine import SlotEngine
    from tritonk8ssupervisor_tpu.serving.gateway import Request

    model = TransformerLM(
        vocab_size=vocab_size,
        num_layers=num_layers,
        num_heads=num_heads,
        embed_dim=embed_dim,
        max_seq_len=max_len,
    )
    draft_model = TransformerLM(
        vocab_size=vocab_size,
        num_layers=draft_layers,
        num_heads=draft_heads,
        embed_dim=draft_embed_dim,
        max_seq_len=max_len,
    )
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, vocab_size, shared_prefix_len)
    prompts = [
        np.concatenate([
            prefix,
            rng.integers(0, vocab_size, prompt_len - shared_prefix_len),
        ]).astype(np.int32)
        for _ in range(requests + 1)  # +1: the warmup request
    ]
    params = model.init(
        jax.random.key(1), jnp.asarray(prompts[0][None, :]), train=False
    )["params"]
    draft_params = draft_model.init(
        jax.random.key(5), jnp.asarray(prompts[0][None, :]), train=False
    )["params"]
    if bias_scale > 0:
        # the shared-bias agreement knob: randomly-initialized models
        # have unrelated argmaxes (acceptance ~ 1/vocab), so a bare
        # A/B would measure the all-reject floor, not the mechanics.
        # A shared strong head bias with one DOMINANT token makes both
        # models follow the same preference deterministically — the
        # high-acceptance synthetic regime (the gaussian part alone is
        # draw-lucky: the target's own logit noise grows with depth
        # and can out-shout it)
        bias_host = rng.normal(0.0, bias_scale, vocab_size)
        bias_host[int(rng.integers(0, vocab_size))] += 10.0 * bias_scale
        bias = jnp.asarray(bias_host, jnp.float32)
        params = jax.tree_util.tree_map(lambda x: x, params)
        draft_params = jax.tree_util.tree_map(lambda x: x, draft_params)
        params["lm_head"] = dict(params["lm_head"])
        params["lm_head"]["bias"] = params["lm_head"]["bias"] + bias
        draft_params["lm_head"] = dict(draft_params["lm_head"])
        draft_params["lm_head"]["bias"] = (
            draft_params["lm_head"]["bias"] + bias
        )

    def drive(engine, stream, budget):
        """Fill slots, step to completion, keep every slot busy —
        the SliceWorker loop without a gateway. Returns outputs in
        request order."""
        pending = list(enumerate(stream))
        done: dict = {}
        inflight: dict = {}
        while pending or inflight:
            for slot in range(engine.slots):
                if slot in inflight or not pending:
                    continue
                rid, tokens = pending[0]
                req = Request(rid=rid, prompt_len=int(tokens.size),
                              max_new_tokens=budget, tokens=tokens)
                if not engine.can_join(req):
                    break
                pending.pop(0)
                engine.join(slot, req)
                inflight[slot] = rid
            result = engine.step()
            if result is None:
                break
            for slot, ids in result.finished.items():
                done[inflight.pop(slot)] = ids
                engine.release(slot)
        return [done[i] for i in sorted(done)]

    def run_mode(name, prefix_cache, budget, use_draft):
        engine = SlotEngine(
            model, params, slots=slots, max_len=max_len,
            prefill_chunk=prefill_chunk, page_size=page_size,
            cache_int8=cache_int8, prefix_cache=prefix_cache,
            draft_model=(draft_model if use_draft else None),
            draft_params=(draft_params if use_draft else None),
            spec_k=(spec_k if use_draft else 0),
        )
        drive(engine, prompts[:1], budget)  # compile + seed the store
        prefill_before = engine.prefill_tokens
        start = time.monotonic()
        outs = drive(engine, prompts[1:], budget)
        elapsed = time.monotonic() - start
        stats = engine.stats()
        total = sum(len(o) for o in outs)
        return {
            "name": name,
            "new_tokens": budget,
            "prefix_cache": prefix_cache,
            "spec_k": spec_k if use_draft else 0,
            "seconds": elapsed,
            "tokens_generated": total,
            "tokens_per_sec": total / elapsed,
            "tokens_per_sec_per_chip": total / elapsed
            / max(1, len(jax.devices())),
            "prefill_tokens": stats["prefill_tokens"] - prefill_before,
            "prefix": stats["prefix"],
            "spec": stats["spec"],
            "outputs": outs,
        }

    results = {
        "cold": run_mode("cold", False, new_tokens, False),
        "warm": run_mode("warm", True, new_tokens, False),
    }
    if spec_k > 0:
        # the decode-heavy speculative budget, clamped so small smoke
        # configs (tiny max_len) still fit prompt + budget in the cache
        spec_budget = max(1, min(spec_new_tokens,
                                 max_len - prompt_len - spec_k))
        results["spec_base"] = run_mode("spec_base", True,
                                        spec_budget, False)
        results["spec"] = run_mode("spec", True, spec_budget, True)
    cold, warm = results["cold"], results["warm"]
    token_identical = cold["outputs"] == warm["outputs"]
    spec_identical = None
    spec_over_baseline = None
    acceptance_rate = None
    if spec_k > 0:
        spec_identical = (results["spec"]["outputs"]
                          == results["spec_base"]["outputs"])
        base_tps = results["spec_base"]["tokens_per_sec"]
        spec_over_baseline = (
            round(results["spec"]["tokens_per_sec"] / base_tps, 3)
            if base_tps else None
        )
        acceptance_rate = (results["spec"]["spec"] or {}).get(
            "acceptance_rate")
    for mode in results.values():
        del mode["outputs"]  # evidence checked, not committed
    aligned = (shared_prefix_len // page_size) * page_size
    hits = (warm["prefix"] or {}).get("hits", 0)
    hit_tokens = (warm["prefix"] or {}).get("hit_tokens", 0)
    reprefilled = hits * aligned - hit_tokens
    speedup = (cold["seconds"] / warm["seconds"]
               if warm["seconds"] else None)
    passes = bool(
        token_identical
        and hits >= requests  # every timed request hit the warm store
        and reprefilled == 0
        and speedup is not None and speedup >= 1.05
        # speculative: exact (token-identical at every acceptance
        # rate), high-acceptance here by construction, and >= 1.4x
        # tokens/sec/chip over the PR-11 paged baseline at matched
        # KV memory — the acceptance criterion the --check gate pins
        and (spec_k == 0 or (
            spec_identical
            and acceptance_rate is not None and acceptance_rate >= 0.8
            and spec_over_baseline is not None
            and spec_over_baseline >= 1.4
        ))
    )
    return {
        "benchmark": "engine_hot_path",
        "metric": "prefix_warm_over_cold_speedup",
        "unit": "x (same shared-system-prompt stream through the REAL "
                "SlotEngine, paged KV both sides; warm = prefix store "
                "seeded, token-identical output required)",
        "platform": jax.default_backend(),
        "num_chips": len(jax.devices()),
        "model": {"vocab_size": vocab_size, "num_layers": num_layers,
                  "num_heads": num_heads, "embed_dim": embed_dim},
        "draft_model": ({"num_layers": draft_layers,
                         "num_heads": draft_heads,
                         "embed_dim": draft_embed_dim}
                        if spec_k > 0 else None),
        "max_len": max_len,
        "prompt_len": prompt_len,
        "shared_prefix_len": shared_prefix_len,
        "new_tokens": new_tokens,
        "requests": requests,
        "slots": slots,
        "page_size": page_size,
        "prefill_chunk": prefill_chunk,
        "cache_int8": bool(cache_int8),
        "bias_scale": float(bias_scale),
        "value": round(speedup, 3) if speedup is not None else None,
        "token_identical": token_identical,
        "shared_prefix_reprefilled_on_hits": int(reprefilled),
        "cold": cold,
        "warm": warm,
        # the speculative block (absent when spec_k == 0): the exact
        # fields the --check structural pin reads
        "speculative": ({
            "metric": "spec_over_paged_baseline_tokens_per_sec_per_chip",
            "unit": "x (decode-heavy stream, prefix-warm both sides, "
                    "matched KV memory; drafter proposes spec_k "
                    "tokens/round, exact accept/reject — "
                    "token-identical required)",
            "spec_k": spec_k,
            "value": spec_over_baseline,
            "token_identical": spec_identical,
            "acceptance_rate": acceptance_rate,
            "baseline": {k: v for k, v in results["spec_base"].items()
                         if k != "name"},
            "spec": {k: v for k, v in results["spec"].items()
                     if k != "name"},
        } if spec_k > 0 else None),
        # machine-readable variant list: one entry per engine mode so
        # future variants (int8, new schedulers) append a row instead
        # of overloading the pairwise keys above
        "modes": [results[name] for name in
                  ("cold", "warm", "spec_base", "spec")
                  if name in results],
        "passes": passes,
    }


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--vocab-size", type=int, default=32768)
    parser.add_argument("--num-layers", type=int, default=12)
    parser.add_argument("--num-heads", type=int, default=12)
    parser.add_argument("--embed-dim", type=int, default=768)
    parser.add_argument("--prompt-len", type=int, default=128)
    parser.add_argument("--new-tokens", type=int, default=512)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument(
        "--int8",
        action="store_true",
        help="weight-only int8 kernels (per-output-channel scales) — "
        "halves the per-token weight read that dominates small-batch "
        "decode",
    )
    parser.add_argument(
        "--unroll",
        type=int,
        default=1,
        help="decode tokens per scan iteration (pure restructuring, "
        "token-identical). Measured NEGATIVE at batch 8 (cache-copy "
        "overhead beats the amortized loop floor), +4%% at batch 1 — "
        "kept as an A/B lever; see docs/benchmarks.md",
    )
    parser.add_argument(
        "--cache-int8",
        action="store_true",
        help="int8 KV cache with per-(token, head) scales — ~1.9x less "
        "cache traffic, the lever for batch >= 8 where the cache read "
        "dominates (weights already amortised across the batch)",
    )
    parser.add_argument(
        "--engine",
        action="store_true",
        help="run the engine-hot-path A/B instead: the real paged "
        "SlotEngine serving a shared-system-prompt stream with the "
        "prefix cache off vs on (token-identical required; "
        "BENCH_engine.json's producer)",
    )
    parser.add_argument(
        "--engine-requests", type=int, default=8,
        help="--engine: timed requests per drive (one extra warms up "
        "compilation and the prefix store)",
    )
    parser.add_argument(
        "--shared-prefix-len", type=int, default=192,
        help="--engine: shared system-prompt tokens opening every "
        "request's prompt",
    )
    parser.add_argument(
        "--spec-k", type=int, default=4,
        help="--engine: drafter tokens per speculative round for the "
        "spec-vs-baseline A/B pair (0 skips the speculative arms)",
    )
    parser.add_argument(
        "--page-size", type=int, default=16,
        help="--engine: KV-page size in tokens (serving/engine.py)",
    )
    parser.add_argument("--json", action="store_true")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # multi-host rendezvous when the Job/ansible env provides coordinates
    # (same contract as the training benchmarks; no-ops on a single host)
    from tritonk8ssupervisor_tpu.parallel import initialize_from_env

    initialize_from_env()
    if args.engine:
        result = run_engine_benchmark(
            requests=args.engine_requests,
            shared_prefix_len=args.shared_prefix_len,
            page_size=args.page_size,
            cache_int8=args.cache_int8,
            spec_k=args.spec_k,
        )
        if args.json:
            print(json.dumps(result, sort_keys=True))
        else:
            print(
                f"engine hot path on {result['platform']}: prefix-warm "
                f"{result['value']}x over cold "
                f"({result['warm']['tokens_per_sec']:.0f} vs "
                f"{result['cold']['tokens_per_sec']:.0f} tok/s), "
                f"token-identical={result['token_identical']}, "
                f"shared-prefix re-prefilled "
                f"{result['shared_prefix_reprefilled_on_hits']} tokens"
            )
            spec = result.get("speculative")
            if spec is not None:
                print(
                    f"speculative k={spec['spec_k']}: "
                    f"{spec['value']}x over the paged baseline "
                    f"({spec['spec']['tokens_per_sec']:.0f} vs "
                    f"{spec['baseline']['tokens_per_sec']:.0f} tok/s), "
                    f"acceptance {spec['acceptance_rate']:.0%}, "
                    f"token-identical={spec['token_identical']}"
                )
        return 0 if result["passes"] else 1
    result = run_benchmark(
        vocab_size=args.vocab_size,
        num_layers=args.num_layers,
        num_heads=args.num_heads,
        embed_dim=args.embed_dim,
        prompt_len=args.prompt_len,
        new_tokens=args.new_tokens,
        batch=args.batch,
        temperature=args.temperature,
        repeats=args.repeats,
        int8=args.int8,
        cache_int8=args.cache_int8,
        unroll=args.unroll,
    )
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        print(
            f"decode on {result['platform']}: batch {result['batch']}, "
            f"{result['decode_tokens_per_sec']:.0f} tok/s, "
            f"{result['ms_per_token_per_stream']:.2f} ms/token/stream "
            f"(prompt {result['prompt_len']}, {result['new_tokens']} new, "
            f"compile {result['compile_seconds']:.1f}s)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
