"""The reference's own VM-level benchmark workloads, reimplemented.

Reference docs/benchmarks.md:1-12 ran misterbisson/simple-container-
benchmarks against each VM: a "/disk" request writing 1 GiB of zeros to
disk and a "/cpu" request md5-hashing 256 MiB of random numbers, reporting
seconds and MB/s per request. Reimplementing them natively (no container
round-trip) keeps the published baseline numbers directly comparable
(BASELINE.md table: Triton 128.8 MB/s disk, 15.96 MB/s cpu).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import time
from pathlib import Path

DISK_BYTES_DEFAULT = 1 << 30       # 1 GiB of zeros (docs/benchmarks.md:8-9)
CPU_BYTES_DEFAULT = 256 << 20      # 256 MiB hashed (docs/benchmarks.md:11-12)
_CHUNK = 4 << 20


def disk_benchmark(path: Path, total_bytes: int = DISK_BYTES_DEFAULT) -> dict:
    """Write zeros to `path`, fsync, report MB/s (the "/disk" request)."""
    chunk = b"\0" * _CHUNK
    start = time.monotonic()
    with path.open("wb") as f:
        written = 0
        while written < total_bytes:
            n = min(_CHUNK, total_bytes - written)
            f.write(chunk[:n])
            written += n
        f.flush()
        os.fsync(f.fileno())
    seconds = time.monotonic() - start
    path.unlink(missing_ok=True)
    return {
        "workload": "disk",
        "bytes": total_bytes,
        "seconds": seconds,
        "mb_per_sec": total_bytes / 1e6 / seconds,
    }


def cpu_benchmark(total_bytes: int = CPU_BYTES_DEFAULT, seed: int = 0) -> dict:
    """md5 over pseudo-random bytes, report MB/s (the "/cpu" request)."""
    rng = int(seed)
    digest = hashlib.md5()
    start = time.monotonic()
    hashed = 0
    while hashed < total_bytes:
        n = min(_CHUNK, total_bytes - hashed)
        # cheap xorshift-filled buffer: "random numbers" per the reference
        # workload without paying os.urandom's syscall cost in the loop
        rng = (rng * 6364136223846793005 + 1442695040888963407) & (2**64 - 1)
        digest.update((rng.to_bytes(8, "little") * ((n + 7) // 8))[:n])
        hashed += n
    seconds = time.monotonic() - start
    return {
        "workload": "cpu",
        "bytes": total_bytes,
        "seconds": seconds,
        "mb_per_sec": total_bytes / 1e6 / seconds,
        "md5": digest.hexdigest(),
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--disk-bytes", type=int, default=DISK_BYTES_DEFAULT)
    parser.add_argument("--cpu-bytes", type=int, default=CPU_BYTES_DEFAULT)
    parser.add_argument("--workdir", type=Path, default=Path("."))
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    results = [
        disk_benchmark(args.workdir / ".containerbench.tmp", args.disk_bytes),
        cpu_benchmark(args.cpu_bytes),
    ]
    if args.json:
        for result in results:
            print(json.dumps(result, sort_keys=True))
    else:
        for result in results:
            print(
                f"/{result['workload']} request: {result['seconds']:.6f}s, "
                f"{result['mb_per_sec']:.2f} MB/s"
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
