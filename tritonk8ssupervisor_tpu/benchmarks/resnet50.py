"""ResNet-50 training-throughput benchmark (images/sec/chip).

The flagship workload prescribed by BASELINE.json — the TPU-native
re-expression of the reference's external benchmark container
(reference docs/benchmarks.md:1-4 ran misterbisson/simple-container-
benchmarks on each VM; here the accelerator is the point). Runs:

- standalone on a TPU VM slice:  python -m tritonk8ssupervisor_tpu.benchmarks.resnet50
- as the GKE Job compiled by config/compile.py to_benchmark_job (the env
  vars it injects are consumed by parallel/distributed.py)
- on CPU for CI smoke (tiny shapes; conftest's 8-device mesh)

Data is synthetic and generated on device: the benchmark measures the
training computation (MXU utilisation + collectives), not host input
pipelines — the standard method for accelerator throughput numbers.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from tritonk8ssupervisor_tpu.models import ResNet18, ResNet50
from tritonk8ssupervisor_tpu.parallel import (
    batch_sharding,
    initialize_from_env,
    make_mesh,
)
from tritonk8ssupervisor_tpu.parallel import train as train_lib
from tritonk8ssupervisor_tpu.parallel.mesh import DATA_AXIS

MODELS = {"resnet50": ResNet50, "resnet18": ResNet18}


def run_benchmark(
    model_name: str = "resnet50",
    batch_per_chip: int = 128,
    image_size: int = 224,
    num_classes: int = 1000,
    steps: int = 30,
    warmup: int = 5,
    model_parallelism: int = 1,
    learning_rate: float = 0.1,
    checkpoint_dir: str | None = None,
) -> dict:
    """Train on synthetic data and measure steady-state throughput.

    Returns a metrics dict; bench.py turns it into the driver JSON line.
    """
    mesh = make_mesh(model_parallelism=model_parallelism)
    num_chips = mesh.devices.size
    data_degree = mesh.shape[DATA_AXIS]
    global_batch = batch_per_chip * data_degree

    model = MODELS[model_name](num_classes=num_classes)
    tx = train_lib.default_optimizer(learning_rate=learning_rate)
    # bf16 input halves the first conv's HBM read (the model computes in
    # bf16 regardless); measured +4% throughput (106 vs 110 ms/step) on v5e
    sample = jax.ShapeDtypeStruct(
        (global_batch, image_size, image_size, 3), jnp.bfloat16
    )
    init_start = time.monotonic()
    state, shardings = train_lib.create_train_state(
        model, jax.random.key(0), sample, mesh, tx
    )
    step = train_lib.make_train_step(model, tx, mesh, shardings)

    # Checkpoint/resume (SURVEY.md §5): resume from the latest step when a
    # checkpoint directory carries one; save after the measured run.
    ckpt = None
    start_step = 0
    restore_seconds = 0.0
    if checkpoint_dir:
        from tritonk8ssupervisor_tpu.parallel.checkpoint import (
            TrainCheckpointer,
            abstract_like,
        )

        restore_start = time.monotonic()
        ckpt = TrainCheckpointer(checkpoint_dir)
        if ckpt.latest_step() is not None:
            state = ckpt.restore(abstract_like(state, shardings))
            start_step = int(state.step)
        # keep compile_seconds comparable across fresh and resumed runs
        restore_seconds = time.monotonic() - restore_start

    # Synthetic batch, born sharded on device (no host->device copies in
    # the timed loop; HBM is the bottleneck we measure, not PCIe).
    image_sh = batch_sharding(mesh, ndim=4)
    label_sh = batch_sharding(mesh, ndim=1)
    k1, k2 = jax.random.split(jax.random.key(1))
    images = jax.device_put(
        jax.random.normal(k1, sample.shape, sample.dtype), image_sh
    )
    labels = jax.device_put(
        jax.random.randint(k2, (global_batch,), 0, num_classes), label_sh
    )

    # The timing fence everywhere below is a host fetch of the loss: the
    # last step's loss depends on every prior step's parameters (donated
    # chaining), and a device->host read cannot complete early —
    # block_until_ready alone is not a reliable fence on remote-tunneled
    # backends.
    state, metrics = step(state, images, labels)  # first step = compile
    float(metrics["loss"])
    compile_seconds = time.monotonic() - init_start - restore_seconds
    for _ in range(max(0, warmup - 1)):  # allocator/queue steady state
        state, metrics = step(state, images, labels)
    float(metrics["loss"])

    start = time.monotonic()
    for _ in range(steps):
        state, metrics = step(state, images, labels)
    final_loss = float(metrics["loss"])
    elapsed = time.monotonic() - start

    if ckpt is not None:
        ckpt.save(int(state.step), state, wait=True)
        ckpt.close()

    images_per_sec = global_batch * steps / elapsed
    return {
        "start_step": start_step,
        "final_step": int(state.step),
        "model": model_name,
        "platform": jax.default_backend(),
        "num_chips": int(num_chips),
        "data_parallelism": int(data_degree),
        "model_parallelism": int(model_parallelism),
        "global_batch": int(global_batch),
        "image_size": image_size,
        "steps": steps,
        "step_ms": elapsed / steps * 1000,
        "images_per_sec": images_per_sec,
        "images_per_sec_per_chip": images_per_sec / num_chips,
        "compile_seconds": compile_seconds,
        "final_loss": final_loss,
    }


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", choices=sorted(MODELS), default="resnet50")
    parser.add_argument("--batch-per-chip", type=int, default=128)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--model-parallelism", type=int, default=1)
    parser.add_argument(
        "--checkpoint-dir",
        default=None,
        help="save TrainState here after the run; resume from it when present",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # multi-host rendezvous when the Job/ansible env provides coordinates
    # (the node-join analogue, SURVEY.md §2.5)
    initialize_from_env()
    result = run_benchmark(
        model_name=args.model,
        batch_per_chip=args.batch_per_chip,
        image_size=args.image_size,
        num_classes=args.num_classes,
        steps=args.steps,
        warmup=args.warmup,
        model_parallelism=args.model_parallelism,
        checkpoint_dir=args.checkpoint_dir,
    )
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        print(
            f"{result['model']} on {result['num_chips']} {result['platform']} "
            f"chip(s): {result['images_per_sec']:.1f} img/s total, "
            f"{result['images_per_sec_per_chip']:.1f} img/s/chip, "
            f"step {result['step_ms']:.1f} ms "
            f"(global batch {result['global_batch']}, compile "
            f"{result['compile_seconds']:.1f}s)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
