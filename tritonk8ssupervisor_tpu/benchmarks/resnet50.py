"""ResNet-50 training-throughput benchmark (images/sec/chip).

The flagship workload prescribed by BASELINE.json — the TPU-native
re-expression of the reference's external benchmark container
(reference docs/benchmarks.md:1-4 ran misterbisson/simple-container-
benchmarks on each VM; here the accelerator is the point). Runs:

- standalone on a TPU VM slice:  python -m tritonk8ssupervisor_tpu.benchmarks.resnet50
- as the GKE Job compiled by config/compile.py to_benchmark_job (the env
  vars it injects are consumed by parallel/distributed.py)
- on CPU for CI smoke (tiny shapes; conftest's 8-device mesh)

Data is synthetic and generated on device: the benchmark measures the
training computation (MXU utilisation + collectives), not host input
pipelines — the standard method for accelerator throughput numbers.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from tritonk8ssupervisor_tpu.utils import perf

from tritonk8ssupervisor_tpu.models import ResNet18, ResNet50, ViT
from tritonk8ssupervisor_tpu.parallel import (
    batch_sharding,
    initialize_from_env,
    make_workload_mesh,
)
from tritonk8ssupervisor_tpu.parallel import train as train_lib
from tritonk8ssupervisor_tpu.parallel import mesh as mesh_lib

# all image-classifier families share this benchmark's harness; "vit"
# is ViT-S/16 (models/vit.py), the transformer vision family
MODELS = {"resnet50": ResNet50, "resnet18": ResNet18, "vit": ViT}


def run_benchmark(
    model_name: str = "resnet50",
    batch_per_chip: int = 128,
    image_size: int = 224,
    num_classes: int = 1000,
    steps: int = 30,
    warmup: int = 5,
    windows: int = 3,
    steps_per_call: int = 0,
    model_parallelism: int = 1,
    learning_rate: float = 0.1,
    fused_1x1_bwd: bool = False,
    remat: bool = False,
    checkpoint_dir: str | None = None,
    profile_dir: str | None = None,
) -> dict:
    """Train on synthetic data and measure steady-state throughput.

    `steps` are timed per measurement window; `windows` independent windows
    (each fenced by a host fetch) give a min/median spread so a 2-3% delta
    between rounds is attributable to the change rather than noise
    (round-2 VERDICT weak #7). FLOPs come from XLA's cost analysis of the
    compiled step and MFU from the chip's bf16 peak (utils/perf.py);
    `profile_dir` captures a jax.profiler trace of a few steady-state steps.

    Returns a metrics dict; bench.py turns it into the driver JSON line.
    """
    mesh = make_workload_mesh(model_parallelism=model_parallelism)
    num_chips = mesh.devices.size
    data_degree = mesh_lib.batch_degree(mesh)
    global_batch = batch_per_chip * data_degree

    # Measured on v5e (100-step windows): per-step dispatch pipelines fine
    # (99.16 ms/step) and the in-graph scan chain is ~0.6 ms/step SLOWER
    # (99.79) — XLA's while-loop aliasing beats nothing here. Auto = 1;
    # the knob stays for hosts where dispatch really is the bottleneck.
    if steps_per_call <= 0:
        steps_per_call = 1
    if steps % steps_per_call:
        raise ValueError(
            f"steps ({steps}) must be a multiple of steps_per_call "
            f"({steps_per_call})"
        )

    model_kwargs = {"num_classes": num_classes, "remat_blocks": remat}
    if model_name.startswith("resnet"):
        model_kwargs["fused_1x1_bwd"] = fused_1x1_bwd
    elif fused_1x1_bwd:
        raise ValueError(
            "--fused-1x1-bwd is a ResNet lever (pallas conv backward); "
            f"{model_name} has no 1x1 convolutions"
        )
    model = MODELS[model_name](**model_kwargs)
    tx = train_lib.default_optimizer(learning_rate=learning_rate)
    # bf16 input halves the first conv's HBM read (the model computes in
    # bf16 regardless); measured +4% throughput (106 vs 110 ms/step) on v5e
    sample = jax.ShapeDtypeStruct(
        (global_batch, image_size, image_size, 3), jnp.bfloat16
    )
    init_start = time.monotonic()
    state, shardings = train_lib.create_train_state(
        model, jax.random.key(0), sample, mesh, tx
    )
    step = train_lib.make_train_step(
        model, tx, mesh, shardings, steps_per_call=steps_per_call
    )

    # Checkpoint/resume (SURVEY.md §5): resume from the latest step when a
    # checkpoint directory carries one; save after the measured run. Lazy
    # import inside the restore window: orbax's first import costs seconds
    # and must hit restore_seconds (subtracted), not compile_seconds.
    ckpt, start_step, restore_seconds = None, 0, 0.0
    if checkpoint_dir:
        restore_start = time.monotonic()
        from tritonk8ssupervisor_tpu.parallel import checkpoint as ckpt_lib

        ckpt, state, start_step, _ = ckpt_lib.maybe_restore(
            checkpoint_dir, state, shardings
        )
        restore_seconds = time.monotonic() - restore_start

    # Synthetic batch, born sharded on device (no host->device copies in
    # the timed loop; HBM is the bottleneck we measure, not PCIe).
    image_sh = batch_sharding(mesh, ndim=4)
    label_sh = batch_sharding(mesh, ndim=1)
    k1, k2 = jax.random.split(jax.random.key(1))
    images = jax.device_put(
        jax.random.normal(k1, sample.shape, sample.dtype), image_sh
    )
    labels = jax.device_put(
        jax.random.randint(k2, (global_batch,), 0, num_classes), label_sh
    )

    # AOT-compile the step: one compilation serves both the run and XLA's
    # cost analysis (FLOPs for the MFU figure) — lowering a second time
    # just for the cost model would double the 20-40s compile.
    compiled = step.lower(state, images, labels).compile()
    flops_per_step = perf.global_flops(compiled, num_chips)

    state, timing = perf.timed_windows(
        lambda s: compiled(s, images, labels),
        state,
        steps=steps,
        warmup=warmup,
        windows=windows,
        steps_per_call=steps_per_call,
        profile_dir=profile_dir,
        on_window=ckpt_lib.window_save_hook(ckpt) if checkpoint_dir else None,
    )
    compile_seconds = (
        timing.pop("first_fence_seconds") - init_start - restore_seconds
    )

    if ckpt is not None:
        ckpt_lib.save_and_close(ckpt, state)

    step_ms = timing["step_ms"]
    images_per_sec = global_batch / (step_ms / 1000)
    return {
        "start_step": start_step,
        "final_step": int(state.step),
        "model": model_name,
        "platform": jax.default_backend(),
        "num_chips": int(num_chips),
        "data_parallelism": int(data_degree),
        "model_parallelism": int(model_parallelism),
        "global_batch": int(global_batch),
        "image_size": image_size,
        **timing,
        "images_per_sec": images_per_sec,
        "images_per_sec_per_chip": images_per_sec / num_chips,
        "flops_per_step": flops_per_step,
        "flops_per_image": (
            flops_per_step / global_batch if flops_per_step else None
        ),
        "mfu": perf.mfu(flops_per_step, step_ms / 1000, num_chips),
        "compile_seconds": compile_seconds,
    }


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", choices=sorted(MODELS), default="resnet50")
    parser.add_argument("--batch-per-chip", type=int, default=128)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--steps", type=int, default=30, help="steps per window")
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--windows", type=int, default=3, help="timed windows")
    parser.add_argument(
        "--steps-per-call",
        type=int,
        default=0,
        help="optimizer steps chained per dispatch via lax.scan "
        "(0 = 1: per-step dispatch; chaining measured slower on v5e)",
    )
    parser.add_argument("--model-parallelism", type=int, default=1)
    parser.add_argument(
        "--fused-1x1-bwd",
        action="store_true",
        help="fused pallas backward for stride-1 1x1 convs "
        "(ops/conv_backward.py) — A/B lever for the bandwidth-bound "
        "backward stages",
    )
    parser.add_argument(
        "--remat",
        action="store_true",
        help="rematerialise residual blocks in the backward "
        "(jax.checkpoint) — A/B lever trading recompute FLOPs for "
        "activation bytes on the HBM-bound step",
    )
    parser.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler trace of steady-state steps into DIR",
    )
    parser.add_argument(
        "--checkpoint-dir",
        default=None,
        help="save TrainState here after the run; resume from it when present",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # multi-host rendezvous when the Job/ansible env provides coordinates
    # (the node-join analogue, SURVEY.md §2.5)
    initialize_from_env()
    result = run_benchmark(
        model_name=args.model,
        batch_per_chip=args.batch_per_chip,
        image_size=args.image_size,
        num_classes=args.num_classes,
        steps=args.steps,
        warmup=args.warmup,
        windows=args.windows,
        steps_per_call=args.steps_per_call,
        model_parallelism=args.model_parallelism,
        fused_1x1_bwd=args.fused_1x1_bwd,
        remat=args.remat,
        checkpoint_dir=args.checkpoint_dir,
        profile_dir=args.profile,
    )
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        print(
            f"{result['model']} on {result['num_chips']} {result['platform']} "
            f"chip(s): {result['images_per_sec']:.1f} img/s total, "
            f"{result['images_per_sec_per_chip']:.1f} img/s/chip, "
            + perf.timing_summary(result)
            + f" (global batch {result['global_batch']}, compile "
            f"{result['compile_seconds']:.1f}s)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
