"""Benchmark workloads.

Two tiers, mirroring the reference's two benchmark ideas:
- containerbench.py — the reference's own VM-level workloads (1 GiB disk
  write, md5 over 256 MiB; reference docs/benchmarks.md:8-12), directly
  comparable against its published numbers.
- resnet50.py — the TPU flagship (BASELINE.json): ResNet-50 training
  throughput in images/sec/chip, standalone on a TPU VM or as the K8s Job
  compiled by config/compile.py.
"""
