"""Autoregressive decoding with a KV cache for TransformerLM.

The serving-side counterpart of the training stack: prefill runs the
prompt through one full-sequence forward (MXU-shaped matmuls) while
writing each layer's K/V into a static-shape cache; decode then steps
one token at a time inside a single `lax.scan` — every step is the same
compiled program (static cache length, masked attention against the
cache), so the whole generation is ONE dispatch, no per-token Python.

TPU-first choices:
- The cache is (layers stacked implicitly per-dict, batch, max_len,
  heads, head_dim) bf16, allocated once; positions beyond `pos` are
  masked with -inf rather than sliced — static shapes keep XLA's tiling
  and avoid recompilation per step.
- Single-token attention is a (1, t)·(t, d) contraction — bandwidth
  bound by the cache read, the canonical decode regime; batching
  decodes amortises it (measured by benchmarks/decode.py).
- Greedy or temperature sampling, both inside the scan
  (jax.random.categorical on the fly; keys split per step).

Parameter layout is models/transformer.py's tree verbatim (Block_i/qkv,
proj, mlp_up, mlp_down, LayerNorm_0/1, tok_embed, pos_embed,
LayerNorm_0, lm_head) — a trained/checkpointed LM decodes without any
conversion. Equivalence with the training forward is pinned by
tests/test_decode.py (greedy continuation == stepwise argmax of the
full forward).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

NEG_INF = -1e30


def init_kv_cache(model, batch: int, max_len: int,
                  int8: bool = False) -> dict:
    """Zeroed per-layer K/V cache: {Block_i: {k, v: (B, L, H, D)}} bf16.

    int8=True stores K/V as int8 with per-(token, head) symmetric f32
    scales ({k, v: int8, k_scale, v_scale: (B, L, H) f32}) — the cache-
    bandwidth lever for the batch>=8 regime where the bf16 cache read
    dominates decode (docs/benchmarks.md decode roofline): 1 byte +
    4/head_dim bytes per element vs 2, a ~1.9x traffic cut at D=64.
    """
    head_dim = model.embed_dim // model.num_heads
    shape = (batch, max_len, model.num_heads, head_dim)
    if int8:
        return {
            f"Block_{i}": {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(shape[:3], jnp.float32),
                "v_scale": jnp.zeros(shape[:3], jnp.float32),
            }
            for i in range(model.num_layers)
        }
    return {
        f"Block_{i}": {
            "k": jnp.zeros(shape, jnp.bfloat16),
            "v": jnp.zeros(shape, jnp.bfloat16),
        }
        for i in range(model.num_layers)
    }


def init_kv_pool(model, num_pages: int, page_size: int,
                 int8: bool = False) -> dict:
    """Zeroed paged K/V pool: {Block_i: {k, v: (P, page_size, H, D)}}
    bf16 — the block-pool replacement for the dense per-slot cache the
    serving engine used to allocate (serving/engine.SlotEngine maps
    slots onto pages through per-slot page tables; short requests stop
    paying max_len rows, and a shared prompt prefix is one set of pages
    referenced by many slots).

    int8=True mirrors init_kv_cache's quantized layout page-wise:
    {k, v: int8 (P, page_size, H, D), k_scale, v_scale: (P, page_size,
    H) f32}. _quant_kv's scales are per-(token, head), so quantizing a
    chunk and scattering values + scales into pages is bit-identical to
    quantizing into the dense cache — paging changes WHERE a token's
    K/V lives, never its value.
    """
    head_dim = model.embed_dim // model.num_heads
    shape = (num_pages, page_size, model.num_heads, head_dim)
    if int8:
        return {
            f"Block_{i}": {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(shape[:3], jnp.float32),
                "v_scale": jnp.zeros(shape[:3], jnp.float32),
            }
            for i in range(model.num_layers)
        }
    return {
        f"Block_{i}": {
            "k": jnp.zeros(shape, jnp.bfloat16),
            "v": jnp.zeros(shape, jnp.bfloat16),
        }
        for i in range(model.num_layers)
    }


def softmax_np(logits, temperature: float = 1.0):
    """Host-side softmax over the last axis (numpy, float64
    accumulation): the acceptance arithmetic of speculative decoding
    runs on the HOST between two compiled dispatches, and the exactness
    proof is about probabilities, so the reference math lives here next
    to the models that produce the logits."""
    import numpy as np

    z = np.asarray(logits, np.float64) / max(1e-8, float(temperature))
    z = z - np.max(z, axis=-1, keepdims=True)
    e = np.exp(z)
    return e / np.sum(e, axis=-1, keepdims=True)


def speculative_accept(draft_tokens, draft_logits, target_logits,
                       temperature: float, rng):
    """Exact accept/reject for one slot's k-token draft — the
    correctness core of speculative decoding (serving/engine.py calls
    this per slot per round; unit-pinned by a chi-square test).

    Inputs: `draft_tokens` (k,) — the drafter's proposals d_1..d_k;
    `draft_logits` (k, V) — the drafter logits each proposal was drawn
    from (ignored when temperature == 0); `target_logits` (k+1, V) —
    the target model's logits at the k+1 verify positions (row i scores
    the candidate at draft index i; row k is the bonus position reached
    only when every draft accepted). `rng` is a numpy Generator (the
    engine's seeded stream).

    Returns (accepted, emitted): `accepted` leading drafts survived and
    `emitted` is those tokens plus EXACTLY ONE more from the target —
    the correction at the first rejected position, or the bonus token.

    Greedy (temperature <= 0): accept d_i iff it equals the target's
    argmax — the emitted chain IS the target-only greedy chain, token
    for token. Sampled: the Leviathan et al. rejection rule — accept
    d_i ~ q with probability min(1, p(d_i)/q(d_i)), else resample from
    norm(max(p - q, 0)). For ANY draft distribution q this yields
    exactly p at every emitted position, which is why the drafter can
    be arbitrarily small/wrong without bending the output distribution
    (only the acceptance rate, i.e. the speed, suffers)."""
    import numpy as np

    draft_tokens = np.asarray(draft_tokens)
    k = int(draft_tokens.shape[0])
    emitted: list[int] = []
    if temperature <= 0:
        ref = np.argmax(np.asarray(target_logits, np.float64), axis=-1)
        accepted = 0
        for i in range(k):
            if int(draft_tokens[i]) != int(ref[i]):
                break
            emitted.append(int(draft_tokens[i]))
            accepted += 1
        emitted.append(int(ref[accepted]))
        return accepted, emitted
    p = softmax_np(target_logits, temperature)  # (k+1, V)
    q = softmax_np(draft_logits, temperature)  # (k, V)
    accepted = 0
    for i in range(k):
        tok = int(draft_tokens[i])
        ratio = p[i, tok] / max(q[i, tok], 1e-300)
        if rng.random() < min(1.0, ratio):
            emitted.append(tok)
            accepted += 1
            continue
        residual = np.maximum(p[i] - q[i], 0.0)
        total = residual.sum()
        if total <= 0.0:
            # p == q at this position: the rejection branch has measure
            # zero; fall back to the target distribution outright
            residual, total = p[i], p[i].sum()
        emitted.append(int(rng.choice(residual.size, p=residual / total)))
        return accepted, emitted
    emitted.append(int(rng.choice(p[k].size, p=p[k] / p[k].sum())))
    return accepted, emitted


def _quant_kv(x):
    """(B, S, H, D) -> (int8 values, (B, S, H) f32 scales): symmetric
    per-(token, head) quantization. The scale rides OUTSIDE the cache
    contraction on both sides of attention — q.(s*k8) == s*(q.k8) on the
    score, probs.(s*v8) == (probs*s).v8 on the value — so the bf16
    dequantized cache is never materialised in HBM."""
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _ln(p, x, dtype):
    return nn.LayerNorm(dtype=dtype, param_dtype=jnp.float32).apply(
        {"params": p}, x
    )


def _dense(p, x, features, dtype):
    if "kernel_int8" in p:
        # weight-only int8: the stored kernel is int8 (half the HBM read
        # of bf16 — decode's bottleneck at small batch); the convert
        # fuses into the dot's operand load, and the per-output-channel
        # scale applies to the OUTPUT column, so the full-precision
        # weight is never materialised: x @ (q * s) == (x @ q) * s.
        y = jnp.einsum("bse,ef->bsf", x.astype(dtype),
                       p["kernel_int8"].astype(dtype))
        y = y * p["scale"].astype(dtype)
        return y + p["bias"].astype(dtype)
    return nn.Dense(features, dtype=dtype, param_dtype=jnp.float32).apply(
        {"params": p}, x
    )


def quantize_params_int8(params: dict) -> dict:
    """Weight-only int8 quantization of every Dense kernel in an LM
    parameter tree (qkv, proj, mlp_up, mlp_down, lm_head) with
    per-output-channel symmetric scales — the serving memory/bandwidth
    lever: decode at small batch re-reads the weights every token, so
    halving their bytes approaches 2x tokens/sec where weights dominate
    (measured in benchmarks/decode.py --int8). Embeddings, positions,
    layernorms and biases stay full precision (a few % of the bytes).
    The quantized tree only runs through this module's decode path;
    training keeps the f32 master weights.
    """
    dense_names = {"qkv", "proj", "mlp_up", "mlp_down", "lm_head"}

    def quant_kernel(kernel):
        scale = jnp.max(jnp.abs(kernel), axis=0) / 127.0
        scale = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(kernel / scale), -127, 127).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    def walk(tree):
        out = {}
        for name, sub in tree.items():
            if name in dense_names and "kernel" in sub:
                q, scale = quant_kernel(sub["kernel"])
                out[name] = {
                    "kernel_int8": q,
                    "scale": scale,
                    "bias": sub["bias"],
                }
            elif isinstance(sub, dict):
                out[name] = walk(sub)
            else:
                out[name] = sub
        return out

    return walk(params)


def _block_with_cache(bp, x, cache_kv, pos, num_heads, mlp_ratio, dtype,
                      prefill: bool):
    """One transformer block over `x` ((B, S, E); S = prompt len in
    prefill, 1 in decode), reading/writing the layer cache.

    prefill=True: causal attention within x + cache write at [0, S).
    prefill=False: x is one token at position `pos`; attention runs
    against cache[0..pos] (static length, masked), cache written at pos.
    """
    b, s, e = x.shape
    head_dim = e // num_heads
    y = _ln(bp["LayerNorm_0"], x, dtype)
    qkv = _dense(bp["qkv"], y, 3 * e, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, num_heads, head_dim)
    k = k.reshape(b, s, num_heads, head_dim)
    v = v.reshape(b, s, num_heads, head_dim)

    int8_cache = "k_scale" in cache_kv
    new_cache = {}
    if prefill:
        if int8_cache:
            kq, ks = _quant_kv(k)
            vq, vs_ = _quant_kv(v)
            new_cache["k"] = jax.lax.dynamic_update_slice(
                cache_kv["k"], kq, (0, 0, 0, 0))
            new_cache["v"] = jax.lax.dynamic_update_slice(
                cache_kv["v"], vq, (0, 0, 0, 0))
            new_cache["k_scale"] = jax.lax.dynamic_update_slice(
                cache_kv["k_scale"], ks, (0, 0, 0))
            new_cache["v_scale"] = jax.lax.dynamic_update_slice(
                cache_kv["v_scale"], vs_, (0, 0, 0))
        else:
            new_cache["k"] = jax.lax.dynamic_update_slice(
                cache_kv["k"], k.astype(jnp.bfloat16), (0, 0, 0, 0))
            new_cache["v"] = jax.lax.dynamic_update_slice(
                cache_kv["v"], v.astype(jnp.bfloat16), (0, 0, 0, 0))
        # causal attention within the prompt — same arithmetic order as
        # ops/ring_attention.attention_reference (the training forward).
        # Runs on the fresh full-precision k/v either way: quantization
        # only affects what later decode steps RE-READ, so prefill
        # logits are exact and the int8 error enters once, not twice.
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            head_dim
        ).astype(q.dtype)
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    else:
        if int8_cache:
            kq, ks = _quant_kv(k)
            vq, vs_ = _quant_kv(v)
            new_k = jax.lax.dynamic_update_slice(
                cache_kv["k"], kq, (0, pos, 0, 0))
            new_v = jax.lax.dynamic_update_slice(
                cache_kv["v"], vq, (0, pos, 0, 0))
            k_scale = jax.lax.dynamic_update_slice(
                cache_kv["k_scale"], ks, (0, pos, 0))
            v_scale = jax.lax.dynamic_update_slice(
                cache_kv["v_scale"], vs_, (0, pos, 0))
            new_cache = {"k": new_k, "v": new_v,
                         "k_scale": k_scale, "v_scale": v_scale}
        else:
            new_k = jax.lax.dynamic_update_slice(
                cache_kv["k"], k.astype(jnp.bfloat16), (0, pos, 0, 0))
            new_v = jax.lax.dynamic_update_slice(
                cache_kv["v"], v.astype(jnp.bfloat16), (0, pos, 0, 0))
            new_cache = {"k": new_k, "v": new_v}
        max_len = new_k.shape[1]
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, new_k.astype(q.dtype)
        ) / jnp.sqrt(head_dim).astype(q.dtype)
        if int8_cache:
            # per-(token, head) K scale applied on the SCORE (the
            # contraction output): (B, L, H) -> (B, H, 1, L)
            scores = scores * k_scale.astype(scores.dtype).transpose(
                0, 2, 1)[:, :, None, :]
        valid = jnp.arange(max_len) <= pos  # static shape, masked tail
        scores = jnp.where(valid[None, None, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        if int8_cache:
            # fold the V scale into probs before the value contraction
            probs = probs * v_scale.astype(probs.dtype).transpose(
                0, 2, 1)[:, :, None, :]
        attn = jnp.einsum(
            "bhqk,bkhd->bqhd", probs.astype(dtype), new_v.astype(dtype)
        )

    x = x + _dense(bp["proj"], attn.reshape(b, s, e), e, dtype)
    y = _ln(bp["LayerNorm_1"], x, dtype)
    y = _dense(bp["mlp_up"], y, mlp_ratio * e, dtype)
    y = nn.gelu(y)
    x = x + _dense(bp["mlp_down"], y, e, dtype)
    return x, new_cache


def _embed(params, tokens, pos_start, model):
    emb = params["tok_embed"]["embedding"]
    x = jnp.take(emb, tokens, axis=0).astype(model.dtype)
    s = tokens.shape[1]
    pos = jax.lax.dynamic_slice_in_dim(
        params["pos_embed"], pos_start, s, axis=0
    )
    return x + pos.astype(model.dtype)


def _head(params, x, model):
    x = _ln(params["LayerNorm_0"], x, model.dtype)
    # the model's configured logits dtype (bf16 by default since r04),
    # NOT hardcoded f32 — near-tie logits round differently in bf16 vs
    # f32 and argmax would pick a different token than the training
    # forward, breaking the token-for-token equivalence claim
    return _dense(
        params["lm_head"], x, model.vocab_size, model.logits_dtype
    )


def prefill(model, params, tokens, max_len: int, cache_int8: bool = False):
    """Run the prompt (B, S) through the stack, filling a length-max_len
    cache. Returns (cache, last_logits (B, vocab)). Prompt attention runs
    on the fresh full-precision k/v, so the last_logits are exact even
    with cache_int8 — quantization error enters only where decode steps
    re-read the cache."""
    b, s = tokens.shape
    if s > max_len:
        raise ValueError(f"prompt length {s} exceeds cache length {max_len}")
    cache = init_kv_cache(model, b, max_len, int8=cache_int8)
    x = _embed(params, tokens, 0, model)
    for i in range(model.num_layers):
        name = f"Block_{i}"
        x, cache[name] = _block_with_cache(
            params[name], x, cache[name], 0,
            model.num_heads, model.mlp_ratio, model.dtype, prefill=True,
        )
    logits = _head(params, x[:, -1:], model)
    return cache, logits[:, 0]


def generate(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    max_len: int | None = None,
    cache_int8: bool = False,
    unroll: int = 1,
) -> jax.Array:
    """Greedy (temperature=0) or sampled continuation of `prompt` (B, S).

    Returns (B, max_new_tokens) int32. jit-able end to end; the decode
    loop is one lax.scan (one compiled step reused for every token).

    cache_int8 stores the KV cache as int8 with per-(token, head) f32
    scales (see init_kv_cache) — ~1.9x less cache traffic, the lever for
    the batch>=8 regime where cache reads dominate. Numerics: per-step
    logit error vs the bf16 cache is bounded by test
    (tests/test_decode.py); greedy continuations can diverge where
    top-2 logits are closer than that bound, as with any quantization.

    `unroll` decodes that many tokens per scan iteration (pure
    restructuring — token-for-token identical output, pinned by test;
    silently 1 when it doesn't divide max_new_tokens). It exists
    because a lax.scan iteration carries a fixed runtime overhead that
    r5 measured at ~380 us on the tunneled dev backend REGARDLESS of
    body size. MEASURED NEGATIVE at batch 8 regardless (8,044 tok/s at
    unroll 1 vs 6,547 at 4): chaining several cache updates in one
    body defeats XLA's in-place aliasing of the carried cache, and the
    resulting copies cost more than the amortized floor; at batch 1
    it is mildly positive (+4%). Default 1; kept as a measured A/B
    lever (docs/benchmarks.md decode section).
    """
    b, s = prompt.shape
    max_len = max_len or model.max_seq_len
    if max_len > model.max_seq_len:
        # past max_seq_len there are no position embeddings; the
        # dynamic slice would silently clamp and reuse the last window
        raise ValueError(
            f"max_len {max_len} exceeds model.max_seq_len "
            f"{model.max_seq_len} (no position embeddings past it)"
        )
    if s + max_new_tokens > max_len:
        raise ValueError(
            f"prompt {s} + new {max_new_tokens} exceeds cache {max_len}"
        )
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng key")
    cache, logits = prefill(model, params, prompt, max_len,
                            cache_int8=cache_int8)
    rng = rng if rng is not None else jax.random.key(0)

    def pick(logits, key):
        if temperature > 0:
            return jax.random.categorical(key, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    def one_token(carry, key):
        cache, logits, pos = carry
        token = pick(logits, key).astype(jnp.int32)  # (B,)
        x = _embed(params, token[:, None], pos, model)
        for i in range(model.num_layers):
            name = f"Block_{i}"
            x, cache[name] = _block_with_cache(
                params[name], x, cache[name], pos,
                model.num_heads, model.mlp_ratio, model.dtype, prefill=False,
            )
        logits = _head(params, x, model)[:, 0]
        return (cache, logits, pos + 1), token

    if unroll > 1 and max_new_tokens % unroll == 0:
        def step(carry, keys_u):
            toks = []
            for u in range(unroll):
                carry, tok = one_token(carry, keys_u[u])
                toks.append(tok)
            return carry, jnp.stack(toks)  # (unroll, B)

        keys = jax.random.split(rng, max_new_tokens)
        keys = keys.reshape(max_new_tokens // unroll, unroll,
                            *keys.shape[1:])
        (_, _, _), tokens = jax.lax.scan(step, (cache, logits, s), keys)
        # (iters, unroll, B) -> (B, max_new_tokens)
        return tokens.reshape(max_new_tokens, -1).T
    keys = jax.random.split(rng, max_new_tokens)
    (_, _, _), tokens = jax.lax.scan(one_token, (cache, logits, s), keys)
    return tokens.T  # (B, max_new_tokens)
