"""Model zoo for the benchmark workloads.

The reference's "workloads" were stateless web apps and generic container
benchmarks (reference docs/detailed.md:255-371, docs/benchmarks.md:1-12).
The TPU-native framework's flagship workload — per BASELINE.json — is
ResNet-50 in JAX, exercised by benchmarks/resnet50.py both standalone on a
TPU VM slice and as a K8s Job (config/compile.py to_benchmark_job).
"""

from tritonk8ssupervisor_tpu.models.moe import MoEMLP
from tritonk8ssupervisor_tpu.models.resnet import ResNet, ResNet18, ResNet50
from tritonk8ssupervisor_tpu.models.transformer import TransformerLM
from tritonk8ssupervisor_tpu.models.vit import ViT

__all__ = [
    "MoEMLP", "ResNet", "ResNet18", "ResNet50", "TransformerLM", "ViT",
]
