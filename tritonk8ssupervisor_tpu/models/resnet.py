"""ResNet in flax.linen, laid out for the TPU MXU.

TPU-first design choices (not tunables — load-bearing for throughput):
- NHWC layout and 3x3/1x1 convs with static shapes: XLA tiles these onto
  the 128x128 MXU directly.
- bfloat16 compute / float32 parameters and batch-norm statistics: the MXU
  natively multiplies bf16 with f32 accumulation, so bf16 halves HBM
  traffic at no accuracy loss for ResNet-scale training.
- No Python control flow that depends on data; the whole forward is one
  traced graph, so `jit` compiles it once per shape.

The reference framework had no model code at all (SURVEY.md §2.5); this is
the flagship benchmark workload prescribed by BASELINE.json (ResNet-50
images/sec/chip on the provisioned slice).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class Conv1x1(nn.Module):
    """A 1x1 convolution phrased as a channel contraction (dot_general).

    Mathematically identical to nn.Conv(features, (1, 1), strides) — same
    parameter name/shape/init, so checkpoints and sharding rules are
    unaffected — but it compiles to XLA's matmul emitter instead of the
    convolution emitters. Measured on v5e (jax.profiler trace of the
    train step): the conv emitters run the *backward* of stage-1 1x1
    convs through sublane-transpose paths at ~4% MXU / ~5x below HBM
    roofline (~25 ms of a 104 ms ResNet-50 step); the same contraction as
    a dot lands on the MXU matmul path. Stride-2 1x1 convs subsample
    before the contraction (exactly what the strided conv computes).
    """

    features: int
    strides: tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        sh, sw = self.strides
        if (sh, sw) != (1, 1):
            x = x[:, ::sh, ::sw, :]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (1, 1, x.shape[-1], self.features),
            self.param_dtype,
        )
        w = kernel[0, 0].astype(self.dtype)
        return jax.lax.dot_general(
            x.astype(self.dtype), w, (((3,), (0,)), ((), ()))
        )


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        # explicit conv names keep the parameter tree identical whether a
        # conv instantiates nn.Conv or Conv1x1 (flax auto-names per class)
        residual = x
        y = self.conv(self.filters, (1, 1), name="Conv_0")(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides, name="Conv_1")(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), name="Conv_2")(y)
        # zero-init the last norm's scale: residual branches start as
        # identity, the standard trick for stable large-batch training
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="shortcut"
            )(x)
            residual = self.norm(name="shortcut_norm")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 block (ResNet-18/34) — the cheap variant for CPU tests."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, name="Conv_0")(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), name="Conv_1")(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="shortcut")(x)
            residual = self.norm(name="shortcut_norm")(residual)
        return nn.relu(residual + y)


class StemConvS2D(nn.Module):
    """The ResNet stem (7x7 stride-2 conv, pad 3) computed space-to-depth.

    Mathematically identical to nn.Conv(features, (7, 7), (2, 2),
    padding=[(3, 3), (3, 3)]) with the same "kernel" parameter
    (7, 7, in, features): the input is rearranged so each 2x2 spatial
    patch becomes 4x the channels — (N, H, W, C) -> (N, H/2, W/2, 4C) —
    and the 7x7 stride-2 kernel becomes a zero-padded 4x4 stride-1 kernel
    over the patch grid. A 3-channel 7x7 stride-2 conv is the worst case
    for the MXU's 128-wide input-feature lanes; the s2d form raises the
    input features 4x and removes the stride. Standard public technique
    for TPU ResNet input layers.
    """

    features: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        n, h, w, c = x.shape
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (7, 7, c, self.features),
            self.param_dtype,
        )
        # taps: out(i) reads x[2i + u - 3], u in [0,7). With u' = u + 1,
        # u' = 2a + r maps each tap to patch offset a-2 and parity r —
        # so pad one zero row/col in front and regroup (8,8,c) as
        # (4,4,4c) with the s2d channel order (r_u, r_v, c).
        w8 = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        w4 = (
            w8.reshape(4, 2, 4, 2, c, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 4 * c, self.features)
        )
        x2 = (
            x.reshape(n, h // 2, 2, w // 2, 2, c)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(n, h // 2, w // 2, 4 * c)
        )
        return jax.lax.conv_general_dilated(
            x2.astype(self.dtype),
            w4.astype(self.dtype),
            window_strides=(1, 1),
            padding=((2, 1), (2, 1)),  # patch offsets a-2 in [-2, 1]
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class FusedBwdConv1x1(nn.Module):
    """Stride-1 1x1 conv with the fused pallas backward
    (ops/conv_backward.py): forward identical to nn.Conv (same
    parameter name/shape/init, same conv_general_dilated), backward
    reads dY once instead of twice. See the kernel module docstring for
    the roofline argument."""

    features: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        from tritonk8ssupervisor_tpu.ops.conv_backward import conv1x1

        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (1, 1, x.shape[-1], self.features),
            self.param_dtype,
        )
        interpret = jax.default_backend() != "tpu"
        return conv1x1(x, kernel, self.dtype, interpret)


class ResNet(nn.Module):
    """Configurable ResNet; `ResNet50()` is the benchmark flagship."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    # 1x1 convs as channel matmuls (Conv1x1). Same math, same parameter
    # tree; measured slightly SLOWER on v5e (107.3 vs 104.4 ms/step,
    # bs 256) because XLA re-fuses the dots into the same layout-
    # constrained fusions — kept as an A/B lever, off by default.
    matmul_1x1: bool = False
    # Space-to-depth stem (StemConvS2D): same math, same parameter tree.
    s2d_stem: bool = True
    # Fused pallas backward for stride-1 1x1 convs (FusedBwdConv1x1):
    # same math, same parameter tree, one dY read instead of two in the
    # backward. Measured on v5e (r04, bs 256): 159.8 vs 99.1 ms/step —
    # the custom call's layout constraints and the defused BN-stat
    # reductions cost ~29 GB/step of extra traffic against ~5 GB saved
    # (docs/benchmarks.md "The 99 ms wall, proven"). Kept as the
    # checked-in evidence + restart point; off by default.
    fused_1x1_bwd: bool = False
    # Rematerialise each residual block in the backward (jax.checkpoint
    # via nn.remat): the bytes-for-FLOPs lever for the HBM-bound step —
    # forward saves only block boundaries, the backward recomputes block
    # internals instead of reading them back. Same math, same parameter
    # tree. A/B lever for the bandwidth-bound backward; measured results
    # in docs/benchmarks.md.
    remat_blocks: bool = False
    # Mask-based stem max-pool backward (ops/pool_backward.py): same
    # forward, elementwise backward instead of XLA's select-and-scatter
    # (measured at ~535 GB/s, the step's one named sub-roofline op).
    # Measured on v5e (r05, bs 256): 139.8 vs 98.8 ms/step — the
    # NEGATIVE result that closes this door: select-and-scatter's
    # traffic is already minimal (x + dy + dx), the tie-count pass adds
    # a full re-read of x, and the 9 interior-dilated f32 accumulation
    # terms defeat XLA's fusion into one pass. The ~0.5 ms rate claw
    # cannot survive a >= 60% byte increase. Kept as the checked-in
    # evidence + A/B lever (docs/benchmarks.md); off by default.
    fast_pool_bwd: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        def conv(features, kernel_size, strides=(1, 1), **kwargs):
            if (
                self.fused_1x1_bwd
                and tuple(kernel_size) == (1, 1)
                and tuple(strides) == (1, 1)
            ):
                return FusedBwdConv1x1(
                    features=features,
                    dtype=self.dtype,
                    name=kwargs.get("name"),
                )
            if self.matmul_1x1 and tuple(kernel_size) == (1, 1):
                return Conv1x1(
                    features=features,
                    strides=tuple(strides),
                    dtype=self.dtype,
                    name=kwargs.get("name"),
                )
            return nn.Conv(
                features,
                kernel_size,
                strides,
                use_bias=False,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                **kwargs,
            )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        if self.s2d_stem and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = StemConvS2D(self.num_filters, dtype=self.dtype,
                            name="stem_conv")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="stem_conv")(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        if (self.fast_pool_bwd and x.shape[1] % 2 == 0
                and x.shape[2] % 2 == 0):
            from tritonk8ssupervisor_tpu.ops.pool_backward import (
                max_pool_3x3_s2,
            )

            x = max_pool_3x3_s2(x)
        else:
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=((1, 1), (1, 1)))
        block_cls = (
            nn.remat(self.block_cls) if self.remat_blocks else self.block_cls
        )
        # explicit names pin the parameter tree to the plain auto-names
        # (nn.remat would otherwise prefix them "Checkpoint...", changing
        # both the tree and the per-module init rng) — remat stays a pure
        # scheduling A/B, checkpoints interchangeable
        base = self.block_cls.__name__
        idx = 0
        for stage, size in enumerate(self.stage_sizes):
            for block in range(size):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = block_cls(
                    filters=self.num_filters * 2**stage,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    name=f"{base}_{idx}",
                )(x)
                idx += 1
        x = jnp.mean(x, axis=(1, 2))
        # logits in f32: the loss softmax needs the dynamic range
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="classifier")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
