"""ResNet in flax.linen, laid out for the TPU MXU.

TPU-first design choices (not tunables — load-bearing for throughput):
- NHWC layout and 3x3/1x1 convs with static shapes: XLA tiles these onto
  the 128x128 MXU directly.
- bfloat16 compute / float32 parameters and batch-norm statistics: the MXU
  natively multiplies bf16 with f32 accumulation, so bf16 halves HBM
  traffic at no accuracy loss for ResNet-scale training.
- No Python control flow that depends on data; the whole forward is one
  traced graph, so `jit` compiles it once per shape.

The reference framework had no model code at all (SURVEY.md §2.5); this is
the flagship benchmark workload prescribed by BASELINE.json (ResNet-50
images/sec/chip on the provisioned slice).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last norm's scale: residual branches start as
        # identity, the standard trick for stable large-batch training
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="shortcut"
            )(x)
            residual = self.norm(name="shortcut_norm")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 block (ResNet-18/34) — the cheap variant for CPU tests."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="shortcut")(x)
            residual = self.norm(name="shortcut_norm")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet; `ResNet50()` is the benchmark flagship."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="stem_conv")(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, size in enumerate(self.stage_sizes):
            for block in range(size):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**stage,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # logits in f32: the loss softmax needs the dynamic range
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="classifier")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
