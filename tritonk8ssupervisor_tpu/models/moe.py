"""Mixture-of-experts MLP with expert parallelism over the mesh.

TPU-first design (GShard/Switch lineage, re-derived for this mesh):

- Routing is static-shaped: every (batch row, expert) pair gets a fixed
  `capacity` of token slots, chosen at trace time, so the whole layer is
  one compiled program — no data-dependent shapes, no host round trips.
  Tokens beyond capacity are dropped (their combine weight is zero and
  the residual stream carries them through unchanged), the standard
  trade for XLA-compilable MoE.
- Dispatch and combine are einsums against a (batch, seq, expert,
  capacity) one-hot. With the batch dim sharded over the mesh's batch
  axes and the expert dim of the dispatched activations + expert
  parameters sharded over "expert" (parallel/mesh.py param_shardings
  routes any parameter whose name contains "expert" there), XLA lowers
  the layout change between them to an all_to_all over ICI — the
  expert-parallel collective, placed by the compiler rather than called
  by hand (same inversion as the gradient psum, SURVEY.md §2.5).
- Router math in float32 (softmax over expert logits is tiny but
  precision-critical); expert FFN math in bf16 like every other matmul.

Aux losses (load-balance + router z-loss) are sown into the
"moe_losses" collection; parallel/train.make_lm_train_step folds every
sown leaf into the optimized loss, so MoE slots into the existing LM
step factory without a new signature.

The reference framework has no MoE (or any model code — SURVEY.md §2.5);
this exists so expert parallelism is a first-class mesh axis alongside
dp/tp/sp/pp rather than a bolt-on.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tritonk8ssupervisor_tpu.parallel.mesh import (
    EXPERT_AXIS,
    batch_axes,
)


def _constraint_mesh(explicit):
    """The mesh to pin MoE layouts against: the module's `mesh` attribute
    when set, else the ambient mesh installed by jax.sharding.use_mesh
    (None when neither exists — sharding propagation alone then decides,
    which XLA resolves by all-gathering the expert weights; fine for
    single-device runs, wasteful on a real expert axis)."""
    if explicit is not None:
        return explicit
    ambient = jax.sharding.get_abstract_mesh()
    return None if ambient.empty else ambient


def compute_capacity(
    seq_len: int, num_experts: int, k: int, capacity_factor: float
) -> int:
    """Token slots per (batch row, expert): ceil(cf * k * s / E), >= 1."""
    return max(1, math.ceil(capacity_factor * k * seq_len / num_experts))


def top_k_dispatch(
    router_probs: jax.Array, k: int, capacity: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Static-shaped top-k routing with per-(row, expert) capacity.

    Args:
      router_probs: (batch, seq, experts) f32 softmax outputs.
      k: choices per token (1 = Switch, 2 = GShard default).
      capacity: slots per (batch row, expert).

    Returns (dispatch, combine, top1_mask):
      dispatch (b, s, E, C) — 0/1; token (b, s) occupies slot c of
        expert e. A token's kth choice only lands after every token's
        (k-1)th choice (choice-major slot ranking), matching the
        priority the gating weights imply.
      combine  (b, s, E, C) — dispatch weighted by the token's
        renormalised gate for that expert (sums to <= 1 over (E, C)).
      top1_mask (b, s, E) — one-hot of each token's first choice, for
        the load-balance loss.
    """
    b, s, e = router_probs.shape
    gates, idx = jax.lax.top_k(router_probs, k)  # (b, s, k)
    masks = jax.nn.one_hot(idx, e, dtype=router_probs.dtype)  # (b, s, k, E)

    # Slot positions: count earlier claims on the same expert, ranking
    # all first choices before any second choice (choice-major), then by
    # sequence position — the deterministic priority order.
    cm = masks.transpose(0, 2, 1, 3).reshape(b, k * s, e)
    pos_cm = jnp.cumsum(cm, axis=1) - cm
    pos = pos_cm.reshape(b, k, s, e).transpose(0, 2, 1, 3)  # (b, s, k, E)
    sel_pos = (pos * masks).sum(-1)  # (b, s, k) slot within chosen expert
    kept = (sel_pos < capacity) * masks.sum(-1)  # (b, s, k) choice kept?

    # Renormalise gates over kept choices so dropped choices don't leak
    # probability mass; a token with every choice dropped contributes 0.
    kept_gate = gates * kept
    denom = jnp.maximum(kept_gate.sum(-1, keepdims=True), 1e-9)
    norm_gates = kept_gate / denom

    slot_oh = jax.nn.one_hot(
        sel_pos.astype(jnp.int32), capacity, dtype=router_probs.dtype
    )
    chosen = masks * kept[..., None]  # (b, s, k, E)
    dispatch = jnp.einsum("bske,bskc->bsec", chosen, slot_oh)
    combine = jnp.einsum("bske,bskc,bsk->bsec", chosen, slot_oh, norm_gates)
    return dispatch, combine, masks[:, :, 0]


def load_balance_loss(
    router_probs: jax.Array, top1_mask: jax.Array
) -> jax.Array:
    """E * sum_e(fraction routed to e * mean prob of e) — minimised (=1)
    at a uniform routing; the Switch/GShard auxiliary."""
    e = router_probs.shape[-1]
    f = top1_mask.reshape(-1, e).mean(0)
    p = router_probs.reshape(-1, e).mean(0)
    return e * jnp.sum(f * p)


class MoEMLP(nn.Module):
    """Drop-in replacement for a transformer MLP: top-k routed experts.

    Parameter names carry "expert" so mesh.param_shardings shards their
    leading expert dim over the "expert" axis (and the FFN width over
    "model" when both divide — ep x tp on the same kernel).
    """

    num_experts: int
    mlp_ratio: int = 4
    k: int = 2
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2
    z_weight: float = 1e-3
    dtype: Any = jnp.bfloat16
    # the device mesh to pin the expert layout against (see
    # _constraint_mesh); optional — without it the layer is still
    # correct, but XLA gathers expert weights instead of all_to_all-ing
    # tokens
    mesh: Any = None

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        e = self.num_experts
        f = self.mlp_ratio * d
        capacity = compute_capacity(s, e, self.k, self.capacity_factor)

        wg = self.param(
            "router_kernel", nn.initializers.lecun_normal(), (d, e),
            jnp.float32,
        )
        w_up = self.param(
            "expert_up_kernel",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, f),
            jnp.float32,
        )
        b_up = self.param(
            "expert_up_bias", nn.initializers.zeros_init(), (e, f),
            jnp.float32,
        )
        w_down = self.param(
            "expert_down_kernel",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, f, d),
            jnp.float32,
        )
        b_down = self.param(
            "expert_down_bias", nn.initializers.zeros_init(), (e, d),
            jnp.float32,
        )

        # Router in f32; the logits feed both the dispatch and the losses.
        logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32), wg)
        probs = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, top1 = top_k_dispatch(probs, self.k, capacity)

        lb = load_balance_loss(probs, top1)
        # z-loss keeps router logits from drifting to magnitudes where
        # the f32 softmax saturates (ST-MoE) — cheap insurance.
        zl = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
        self.sow(
            "moe_losses",
            "router",
            self.aux_weight * lb + self.z_weight * zl,
        )

        # (b, s, d) batch-sharded -> (E, b, C, d) expert-sharded: with
        # the layout pinned below, XLA lowers this boundary to an
        # all_to_all (tokens travel; weights stay put).
        mesh = _constraint_mesh(self.mesh)
        if mesh is not None and EXPERT_AXIS in mesh.axis_names:
            from jax.sharding import Mesh

            def pin(t, *spec):
                if isinstance(mesh, Mesh):
                    return jax.lax.with_sharding_constraint(
                        t, NamedSharding(mesh, P(*spec))
                    )
                return jax.lax.with_sharding_constraint(t, P(*spec))

            # batch rows stay over "data" in the expert layout; the
            # expert dim takes over the "expert" axis
            expert_row = tuple(
                a for a in batch_axes(mesh) if a != EXPERT_AXIS
            )
        else:
            def pin(t, *spec):
                return t

            expert_row = ()

        xe = jnp.einsum(
            "bsec,bsd->ebcd", dispatch.astype(self.dtype), x.astype(self.dtype)
        )
        xe = pin(xe, EXPERT_AXIS, expert_row, None, None)
        h = jnp.einsum("ebcd,edf->ebcf", xe, w_up.astype(self.dtype))
        h = h + b_up.astype(self.dtype)[:, None, None, :]
        h = nn.gelu(h)
        y = jnp.einsum("ebcf,efd->ebcd", h, w_down.astype(self.dtype))
        y = y + b_down.astype(self.dtype)[:, None, None, :]
        y = pin(y, EXPERT_AXIS, expert_row, None, None)
        # expert-sharded -> batch-sharded (the second all_to_all), with
        # the gate weights folded in
        out = jnp.einsum("bsec,ebcd->bsd", combine.astype(self.dtype), y)
        if mesh is not None and EXPERT_AXIS in mesh.axis_names:
            out = pin(out, batch_axes(mesh), None, None)
        return out


def moe_mlp_reference(variables: dict, x: jax.Array, k: int) -> jax.Array:
    """Per-token reference for tests: same math as MoEMLP with unlimited
    capacity (no drops), computed the naive way — every expert applied to
    every token, gathered by gate. f32 throughout."""
    p = variables["params"]
    logits = jnp.einsum("bsd,de->bse", x, p["router_kernel"])
    probs = jax.nn.softmax(logits, axis=-1)
    gates, idx = jax.lax.top_k(probs, k)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)

    h = jnp.einsum("bsd,edf->ebsf", x, p["expert_up_kernel"])
    h = h + p["expert_up_bias"][:, None, None, :]
    h = nn.gelu(h)
    y = jnp.einsum("ebsf,efd->ebsd", h, p["expert_down_kernel"])
    y = y + p["expert_down_bias"][:, None, None, :]  # (E, b, s, d)

    sel = jnp.take_along_axis(
        y.transpose(1, 2, 0, 3),  # (b, s, E, d)
        idx[..., None],
        axis=2,
    )  # (b, s, k, d)
    return jnp.einsum("bskd,bsk->bsd", sel, gates)


def upcycle_dense_to_moe(
    dense_params: dict,
    moe_model,
    rng: jax.Array,
) -> dict:
    """Sparse upcycling: initialise a MoE TransformerLM/ViT from a dense
    checkpoint with the same depth/width — every expert starts as a copy
    of the dense block's MLP, the router starts fresh, and all non-MoE
    parameters transfer verbatim. The upcycled model computes (near-)
    the same function at step 0 (top-k of identical experts ≈ the dense
    MLP), then the experts differentiate as training routes tokens —
    the standard public recipe for growing capacity from a trained
    dense model.

    Args:
      dense_params: params tree of the dense twin (same num_layers,
        embed_dim, mlp_ratio; dense MLPs in every block).
      moe_model: the target model config (moe_experts > 0).
      rng: key for the fresh router kernels.

    Returns the MoE model's params tree.
    """
    e = moe_model.moe_experts
    if not e:
        raise ValueError("moe_model.moe_experts must be > 0 to upcycle")
    out = dict(dense_params)
    # which blocks become MoE is the model config's placement rule —
    # derived here directly (no init call, so the same code serves the
    # token-input LM and the image-input ViT)
    for i in range(moe_model.num_layers):
        if (i + 1) % moe_model.moe_every:
            continue
        name = f"Block_{i}"
        dense_block = dense_params[name]
        up_k = dense_block["mlp_up"]["kernel"]  # (d, f)
        rng, sub = jax.random.split(rng)
        moe = {
            # fresh router; everything else copies the dense MLP into
            # every expert (biases ride along)
            "router_kernel": nn.initializers.lecun_normal()(
                sub, (up_k.shape[0], e), jnp.float32
            ),
            "expert_up_kernel": jnp.broadcast_to(
                up_k[None], (e, *up_k.shape)
            ).copy(),
            "expert_up_bias": jnp.broadcast_to(
                dense_block["mlp_up"]["bias"][None],
                (e, up_k.shape[1]),
            ).copy(),
            "expert_down_kernel": jnp.broadcast_to(
                dense_block["mlp_down"]["kernel"][None],
                (e, up_k.shape[1], up_k.shape[0]),
            ).copy(),
            "expert_down_bias": jnp.broadcast_to(
                dense_block["mlp_down"]["bias"][None],
                (e, up_k.shape[0]),
            ).copy(),
        }
        new_block = {
            k: v for k, v in dense_block.items()
            if k not in ("mlp_up", "mlp_down")
        }
        new_block["moe_mlp"] = moe
        out[name] = new_block
    return out
