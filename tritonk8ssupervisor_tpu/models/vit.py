"""Vision Transformer (ViT) classifier — the third model family.

Reuses the LM's transformer Block (models/transformer.py) with
bidirectional attention, so every attention strategy and parallelism
lever the LM has (dense/flash kernels, tensor-sharded wide params,
remat, MoE MLPs) applies to vision with zero extra wiring. TPU layout
notes: patchify is one stride-P conv (a single MXU matmul over the
patch pixels); embed widths stay multiples of 128 (lane width); compute
bf16, params f32, classifier head f32 for the softmax — the same
discipline as the other families.

The reference framework has no model code (SURVEY.md §2.5); this family
exists so the zoo covers the standard vision-transformer recipe next to
the conv (ResNet) and language (TransformerLM/MoE) families.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from tritonk8ssupervisor_tpu.models.transformer import Block, dense_attention


class ViT(nn.Module):
    """images (B, H, W, C) -> logits (B, num_classes).

    Standard recipe: patchify conv -> [CLS] token + learned positions ->
    pre-norm transformer blocks -> final norm -> take [CLS] -> linear
    head. ViT-S/16-class defaults sized so CPU tests stay fast when
    shrunk and the 224x224 configuration is real.
    """

    num_classes: int = 1000
    patch_size: int = 16
    num_layers: int = 12
    num_heads: int = 6
    embed_dim: int = 384
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    # any of the LM's attention strategies plug in here; blocks run with
    # causal=False (classification has no causal order), so the flag is
    # honored by whichever strategy is passed rather than overridden in
    # a wrapper
    attention_fn: Any = dense_attention
    # same levers as TransformerLM (see its field comments)
    moe_experts: int = 0
    moe_every: int = 2
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_mesh: Any = None
    remat_blocks: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, h, w, _ = x.shape
        p = self.patch_size
        if h % p or w % p:
            raise ValueError(f"image {h}x{w} not divisible by patch {p}")
        # patchify: one stride-p conv == per-patch linear projection
        x = nn.Conv(
            self.embed_dim, (p, p), strides=(p, p), padding="VALID",
            dtype=self.dtype, param_dtype=jnp.float32, name="patch_embed",
        )(x.astype(self.dtype))
        x = x.reshape(b, -1, self.embed_dim)  # (B, patches, D)
        n = x.shape[1]

        cls = self.param(
            "cls_token", nn.initializers.zeros_init(), (1, 1, self.embed_dim),
            jnp.float32,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype), (b, 1, self.embed_dim)), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (n + 1, self.embed_dim), jnp.float32,
        )
        x = x + pos.astype(self.dtype)

        block_cls = nn.remat(Block) if self.remat_blocks else Block
        for i in range(self.num_layers):
            moe_here = self.moe_experts and (i + 1) % self.moe_every == 0
            x = block_cls(
                num_heads=self.num_heads,
                attention_fn=self.attention_fn,
                mlp_ratio=self.mlp_ratio,
                dtype=self.dtype,
                causal=False,
                moe_experts=self.moe_experts if moe_here else 0,
                moe_k=self.moe_k,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_mesh=self.moe_mesh,
                name=f"Block_{i}",
            )(x)

        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        # classification reads the [CLS] position; logits f32 for the loss
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
            name="classifier",
        )(x[:, 0])
