"""Decoder-only Transformer LM with pluggable attention.

The second model family (the flagship benchmark is ResNet-50 — BASELINE.json);
this one exists to exercise the long-context path: pass a ring-attention
closure (ops/ring_attention.py) as `attention_fn` and the sequence axis
shards across the mesh — per-device activation memory scales as O(S/n)
while the math stays exact.

TPU layout notes: embeddings and MLP widths stay multiples of 128 (lane
width) so XLA tiles them onto the MXU; compute in bf16, params in f32.
Logits default to bf16 since r04 (the loss kernel does f32 math per
block; see the lm_head comment) — `logits_dtype=float32` restores the
f32 head.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from tritonk8ssupervisor_tpu.ops.ring_attention import attention_reference

# attention_fn signature: (q, k, v, causal) -> out, all (B, S, H, D).
# Strategies used with Block.head_major=True must also accept
# layout="bshd"|"bhsd" and run on (B, H, S, D) when "bhsd"
# (ops/flash_attention.py and dense_attention do; the ring is
# seq-major only).
AttentionFn = Callable[..., Any]


def dense_attention(q, k, v, causal: bool = True, layout: str = "bshd"):
    from tritonk8ssupervisor_tpu.ops.ring_attention import (
        attention_reference_layout,
    )

    return attention_reference_layout(q, k, v, causal, layout)


class _HeadMajorQKV(nn.Module):
    """The qkv projection producing (b, h, s, d) q/k/v directly: the SAME
    (embed, 3*embed) kernel and (3*embed,) bias nn.Dense would declare —
    module path and param names identical, so init values and
    checkpoints are interchangeable with the seq-major path — consumed
    reshaped per head, so the head-major layout comes out of the matmul
    instead of a separate relayout pass over HBM."""

    num_heads: int
    dtype: Any

    @nn.compact
    def __call__(self, y):
        e = y.shape[-1]
        d = e // self.num_heads
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (e, 3 * e),
            jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (3 * e,), jnp.float32
        )
        w = kernel.reshape(e, 3, self.num_heads, d).astype(self.dtype)
        b3 = bias.reshape(3, self.num_heads, d).astype(self.dtype)
        out = jnp.einsum("bse,ekhd->kbhsd", y.astype(self.dtype), w)
        out = out + b3[:, None, :, None, :]
        return out[0], out[1], out[2]


class _HeadMajorProj(nn.Module):
    """The attention output projection contracting straight from
    (b, h, s, d): same (embed, embed) kernel / (embed,) bias as
    nn.Dense(name="proj"), so the tree is unchanged; the back-relayout
    folds into the matmul."""

    dtype: Any

    @nn.compact
    def __call__(self, attn):
        b, h, s, d = attn.shape
        e = h * d
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (e, e), jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (e,), jnp.float32
        )
        w = kernel.reshape(h, d, e).astype(self.dtype)
        return (
            jnp.einsum("bhsd,hde->bse", attn, w) + bias.astype(self.dtype)
        )


class Block(nn.Module):
    num_heads: int
    attention_fn: AttentionFn
    mlp_ratio: int
    dtype: Any
    # causal masking flag forwarded to attention_fn: True for LMs,
    # False for bidirectional consumers (ViT) — held here so EVERY
    # attention strategy honors it rather than each consumer wrapping
    # attention_fn to override it
    causal: bool = True
    # head-major attention layout: q/k/v are produced as (b, h, s, d) by
    # einsumming the SAME qkv kernel reshaped per head (parameter tree
    # unchanged, checkpoints interchangeable), and the output projection
    # contracts straight from (b, h, s, d) — the (b,s,h,d)<->(b,h,s,d)
    # relayouts around head-major kernels (splash) disappear instead of
    # costing HBM passes. attention_fn must accept layout="bhsd"
    # (ops/flash_attention.py does).
    # MEASURED on v5e (seq 1024 b8 LM step): 67.1 ms vs 62.7 seq-major —
    # pinning the projection's output layout costs XLA more inside the
    # dots than the explicit transposes it removes (the r04 roofline's
    # 4.2 ms "data formatting" was already near-optimal). Kept as an A/B
    # lever + evidence, default off.
    head_major: bool = False
    # > 0 replaces this block's dense MLP with a mixture of experts
    # (models/moe.py) — expert parameters shard over the mesh's "expert"
    # axis, dispatch/combine become all_to_alls
    moe_experts: int = 0
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_mesh: Any = None

    @nn.compact
    def __call__(self, x):
        b, s, e = x.shape
        head_dim = e // self.num_heads
        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32)

        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        if self.head_major:
            q, k, v = _HeadMajorQKV(
                num_heads=self.num_heads, dtype=self.dtype, name="qkv"
            )(y)
            attn = self.attention_fn(
                q, k, v, causal=self.causal, layout="bhsd"
            )
            x = x + _HeadMajorProj(dtype=self.dtype, name="proj")(attn)
        else:
            qkv = dense(3 * e, name="qkv")(y)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, s, self.num_heads, head_dim)
            k = k.reshape(b, s, self.num_heads, head_dim)
            v = v.reshape(b, s, self.num_heads, head_dim)
            attn = self.attention_fn(q, k, v, causal=self.causal)
            x = x + dense(e, name="proj")(attn.reshape(b, s, e))

        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        if self.moe_experts:
            from tritonk8ssupervisor_tpu.models.moe import MoEMLP

            y = MoEMLP(
                num_experts=self.moe_experts,
                mlp_ratio=self.mlp_ratio,
                k=self.moe_k,
                capacity_factor=self.moe_capacity_factor,
                dtype=self.dtype,
                mesh=self.moe_mesh,
                name="moe_mlp",
            )(y)
            return x + y
        y = dense(self.mlp_ratio * e, name="mlp_up")(y)
        y = nn.gelu(y)
        x = x + dense(e, name="mlp_down")(y)
        return x


class TransformerLM(nn.Module):
    """Causal LM: tokens (batch, seq) int32 -> logits (batch, seq, vocab)."""

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    embed_dim: int = 512
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    attention_fn: AttentionFn = dense_attention
    dtype: Any = jnp.bfloat16
    # dtype of the returned logits; see the lm_head comment below for
    # why bf16 is the default (float32 restores the r03 head)
    logits_dtype: Any = jnp.bfloat16
    # moe_experts > 0 makes every `moe_every`-th block (the 2nd, 4th, ...
    # — the GShard placement) a mixture-of-experts block; the router aux
    # losses land in the "moe_losses" collection, which
    # parallel/train.make_lm_train_step folds into the optimized loss
    moe_experts: int = 0
    moe_every: int = 2
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    # mesh to pin the MoE expert layout against (models/moe.py
    # _constraint_mesh); optional
    moe_mesh: Any = None
    # rematerialise each block in the backward (jax.checkpoint): trades
    # recompute FLOPs for activation bytes — the long-context lever when
    # saved per-layer activations dominate HBM
    remat_blocks: bool = False
    # head-major attention layout (see Block.head_major): q/k/v born
    # (b, h, s, d) from the projection, no relayout around head-major
    # kernels; attention_fn must accept layout="bhsd"
    head_major: bool = False

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        b, s = tokens.shape
        tok = nn.Embed(
            self.vocab_size,
            self.embed_dim,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="tok_embed",
        )(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_seq_len, self.embed_dim),
            jnp.float32,
        )
        x = tok + pos[:s].astype(self.dtype)
        block_cls = nn.remat(Block) if self.remat_blocks else Block
        for i in range(self.num_layers):
            moe_here = self.moe_experts and (i + 1) % self.moe_every == 0
            # explicit Block_i names pin the tree across the remat A/B
            # (nn.remat would auto-name "CheckpointBlock_i") and match
            # what parallel/pipeline.py slices by name
            x = block_cls(
                num_heads=self.num_heads,
                attention_fn=self.attention_fn,
                mlp_ratio=self.mlp_ratio,
                dtype=self.dtype,
                moe_experts=self.moe_experts if moe_here else 0,
                moe_k=self.moe_k,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_mesh=self.moe_mesh,
                head_major=self.head_major,
                name=f"Block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        # bf16 logits: at LM vocab the logits are the program's biggest
        # array ((batch*seq, 32k) = 0.5 GB at the benchmark shape), and
        # every consumer re-reads it — loss kernel, its backward, the
        # head's wgrad. r04 roofline: those passes run at HBM peak, so
        # f32 logits cost ~3 ms/step of pure bandwidth. The loss kernel
        # upcasts per block (f32 math inside), so only the stored array
        # is rounded; set logits_dtype=float32 to keep the old head.
        logits = nn.Dense(
            self.vocab_size, dtype=self.logits_dtype, param_dtype=jnp.float32,
            name="lm_head",
        )(x)
        return logits
