"""Decoder-only Transformer LM with pluggable attention.

The second model family (the flagship benchmark is ResNet-50 — BASELINE.json);
this one exists to exercise the long-context path: pass a ring-attention
closure (ops/ring_attention.py) as `attention_fn` and the sequence axis
shards across the mesh — per-device activation memory scales as O(S/n)
while the math stays exact.

TPU layout notes: embeddings and MLP widths stay multiples of 128 (lane
width) so XLA tiles them onto the MXU; compute in bf16, params in f32.
Logits default to bf16 since r04 (the loss kernel does f32 math per
block; see the lm_head comment) — `logits_dtype=float32` restores the
f32 head.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from tritonk8ssupervisor_tpu.ops.ring_attention import attention_reference

# attention_fn signature: (q, k, v, causal) -> out, all (B, S, H, D)
AttentionFn = Callable[..., Any]


def dense_attention(q, k, v, causal: bool = True):
    return attention_reference(q, k, v, causal=causal)


class Block(nn.Module):
    num_heads: int
    attention_fn: AttentionFn
    mlp_ratio: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        b, s, e = x.shape
        head_dim = e // self.num_heads
        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32)

        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        qkv = dense(3 * e, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, head_dim)
        k = k.reshape(b, s, self.num_heads, head_dim)
        v = v.reshape(b, s, self.num_heads, head_dim)
        attn = self.attention_fn(q, k, v, causal=True)
        x = x + dense(e, name="proj")(attn.reshape(b, s, e))

        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        y = dense(self.mlp_ratio * e, name="mlp_up")(y)
        y = nn.gelu(y)
        x = x + dense(e, name="mlp_down")(y)
        return x


class TransformerLM(nn.Module):
    """Causal LM: tokens (batch, seq) int32 -> logits (batch, seq, vocab)."""

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    embed_dim: int = 512
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    attention_fn: AttentionFn = dense_attention
    dtype: Any = jnp.bfloat16
    # dtype of the returned logits; see the lm_head comment below for
    # why bf16 is the default (float32 restores the r03 head)
    logits_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        b, s = tokens.shape
        tok = nn.Embed(
            self.vocab_size,
            self.embed_dim,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="tok_embed",
        )(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_seq_len, self.embed_dim),
            jnp.float32,
        )
        x = tok + pos[:s].astype(self.dtype)
        for _ in range(self.num_layers):
            x = Block(
                num_heads=self.num_heads,
                attention_fn=self.attention_fn,
                mlp_ratio=self.mlp_ratio,
                dtype=self.dtype,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        # bf16 logits: at LM vocab the logits are the program's biggest
        # array ((batch*seq, 32k) = 0.5 GB at the benchmark shape), and
        # every consumer re-reads it — loss kernel, its backward, the
        # head's wgrad. r04 roofline: those passes run at HBM peak, so
        # f32 logits cost ~3 ms/step of pure bandwidth. The loss kernel
        # upcasts per block (f32 math inside), so only the stored array
        # is rounded; set logits_dtype=float32 to keep the old head.
        logits = nn.Dense(
            self.vocab_size, dtype=self.logits_dtype, param_dtype=jnp.float32,
            name="lm_head",
        )(x)
        return logits
