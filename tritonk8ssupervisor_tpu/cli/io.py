"""Terminal prompt primitives.

The reference built its whole wizard on one helper, `getArgument`
(reference setup.sh:94-110): ``read -p "prompt [default]: "`` with
empty-input-means-default semantics, plus hand-rolled numbered menus for
networks/packages (setup.sh:309-450) and a literal-"yes" confirmation gate
(setup.sh:471-482). This module gives the same three primitives as a class
with injectable streams so the wizard is unit-testable with scripted input
— the test seam the reference never had (SURVEY.md §4).
"""

from __future__ import annotations

import sys
from typing import Callable, Sequence, TextIO


class EndOfInput(RuntimeError):
    """Input stream exhausted mid-wizard (non-interactive misuse)."""


class Prompter:
    def __init__(self, in_stream: TextIO | None = None, out: TextIO | None = None):
        self._in = in_stream if in_stream is not None else sys.stdin
        self._out = out if out is not None else sys.stdout

    # -- low level ---------------------------------------------------------

    def say(self, text: str = "") -> None:
        print(text, file=self._out, flush=True)

    def _readline(self) -> str:
        line = self._in.readline()
        if line == "":
            raise EndOfInput("ran out of input while prompting")
        return line.rstrip("\n")

    # -- getArgument analogue (setup.sh:94-110) ----------------------------

    def ask(self, label: str, default: str = "") -> str:
        suffix = f" [{default}]" if default else ""
        print(f"{label}{suffix}: ", end="", file=self._out, flush=True)
        answer = self._readline().strip()
        return answer if answer else default

    def ask_validated(
        self,
        label: str,
        default: str,
        validate: Callable[[str], str],
    ) -> str:
        """Re-prompt until `validate` accepts (returns an error string to
        reject, "" to accept) — the reference's per-field while loops
        (e.g. hostname regex retry, setup.sh:276-283)."""
        while True:
            answer = self.ask(label, default)
            error = validate(answer)
            if not error:
                return answer
            self.say(f"  ! {error}")

    # -- numbered menu (setup.sh:309-450 analogue) -------------------------

    def menu(self, title: str, options: Sequence[str], default_index: int = 0) -> int:
        """Print a numbered menu, return the chosen 0-based index.

        Out-of-range or non-numeric input re-prompts, like the reference's
        menu bounds checks (setup.sh:337-356, 428-448).
        """
        self.say(title)
        for i, option in enumerate(options):
            marker = "*" if i == default_index else ""
            self.say(f"  {i + 1}) {option} {marker}".rstrip())
        while True:
            raw = self.ask("Select", str(default_index + 1))
            try:
                choice = int(raw)
            except ValueError:
                self.say(f"  ! enter a number 1-{len(options)}")
                continue
            if 1 <= choice <= len(options):
                return choice - 1
            self.say(f"  ! enter a number 1-{len(options)}")

    # -- confirmation gate (setup.sh:471-482 analogue) ---------------------

    def confirm(self, question: str) -> bool:
        """True only on literal yes/y — the reference required literal "yes"
        and treated anything else as abort (setup.sh:471-482)."""
        answer = self.ask(f"{question} (yes/no)", "no").lower()
        return answer in ("yes", "y")
