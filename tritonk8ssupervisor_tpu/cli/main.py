"""Pipeline orchestrator — the rebuild of `main` (reference setup.sh:8-92).

Same phases as the reference (SURVEY.md §3.1): previous-run guard →
environment discovery → wizard → human verification gate → persist config →
then the provisioning phases — terraform apply, host configuration
(ansible), readiness wait, manifest compilation, probe job. Unlike the
reference's strict line, the provisioning phases run as a dependency DAG
(provision/scheduler.py), and since PR 4 the tpu-vm pipeline is
incremental along two axes:

- **Per-slice pipelined convergence**: readiness and ansible run per
  slice (`readiness-slice-N`, `configure-slice-N` after a short shared
  `host-prep`), so slice 0 configures while slice 3 is still booting —
  the old single `host-configuration` barrier waited for EVERY slice's
  ssh before configuring ANY of them.
- **Content-addressed warm path** (provision/cache.py): compile and
  per-slice converge are no-ops when their content keys already
  converged, and the durable journal (provision/journal.py) skips the
  verified prefix on resume — provision, heal, and crash-resume share
  one skip logic.

Every phase is timed with overlap-aware spans (utils/phases.py), since
wall-clock-to-ready is the north-star metric and the DAG's makespan —
not the sum of phases — is that number. See docs/performance.md for the
graph, the cold-vs-warm numbers, and how to read the runlog.

`./setup.sh -c` dispatches to teardown (cleanRunner analogue,
setup.sh:9-12, 484-521).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import shlex
import signal
import sys
import time
from pathlib import Path

from tritonk8ssupervisor_tpu.cli import discovery, wizard
from tritonk8ssupervisor_tpu.cli.io import EndOfInput, Prompter
from tritonk8ssupervisor_tpu.config import compile as compiler
from tritonk8ssupervisor_tpu.config import store
from tritonk8ssupervisor_tpu.config.schema import ClusterConfig, ConfigError
from tritonk8ssupervisor_tpu.provision import (
    ansible as ansible_mod,
    cache as cache_mod,
    events as events_mod,
    heal as heal_mod,
    journal as journal_mod,
    readiness,
    retry,
    runner as run_mod,
    state,
    supervisor as supervisor_mod,
    teardown,
    terraform as terraform_mod,
)
from tritonk8ssupervisor_tpu.provision.scheduler import Task, run_dag
from tritonk8ssupervisor_tpu.testing import faults
from tritonk8ssupervisor_tpu.utils.phases import PhaseTimer


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="setup.sh",
        description="Provision a TPU-backed Kubernetes cluster on GCP.",
    )
    # the reference's single flag (setup.sh:9-12)
    parser.add_argument(
        "command",
        nargs="?",
        choices=["heal", "supervise", "status", "train", "serve",
                 "trace", "analyze"],
        metavar="command",
        help="optional subcommand: `heal` diagnoses per-slice fleet "
        "health (missing / unready / draining) and repairs ONLY the "
        "broken slices — scoped terraform replace, ansible --limit, "
        "scoped readiness — leaving healthy slices untouched; "
        "`supervise` runs the resident reconcile loop (detect drift, "
        "rate-limited auto-heal, circuit breaker, durable event ledger); "
        "`status` renders the machine-readable fleet status "
        "(docs/failure-modes.md, running-unattended runbook); `train` "
        "runs the elastic-training drill — a small LM trained through "
        "parallel/elastic.py's ElasticTrainer against this workdir's "
        "fleet-status.json, resuming at the new world size on membership "
        "changes (docs/failure-modes.md, elastic-training runbook); "
        "`serve` runs the continuous-batching inference gateway "
        "(serving/gateway.py) in front of the KV-cache decode stack, "
        "routed by this workdir's fleet-status.json — HTTP POST "
        "/generate by default, or --drill N for a no-network smoke "
        "(docs/performance.md, Serving); `trace <key>` reconstructs "
        "one request's end-to-end timeline from the span log + request "
        "journal (docs/observability.md); `analyze` summarises the "
        "span log, and with --correlate joins supervisor ledger events "
        "with request spans to attribute latency spikes to fleet "
        "events",
    )
    parser.add_argument(
        "arg", nargs="?", default=None, metavar="key",
        help="trace: the request idempotency key to reconstruct",
    )
    parser.add_argument(
        "-c", "--clean", action="store_true", help="destroy the cluster and all state"
    )
    parser.add_argument(
        "--max-degraded",
        type=int,
        default=0,
        metavar="N",
        help="heal: tolerate up to N slices that stay broken after "
        "repair — they are quarantined (terraform/quarantine.json) and "
        "emptied from hosts.json, and heal succeeds on the remaining "
        "healthy slices instead of aborting (N-of-M semantics)",
    )
    parser.add_argument(
        "--yes", action="store_true", help="skip confirmation gates (CI use)"
    )
    # ------------------------------------------------- supervise / status
    # Defaults of None mean "take the SupervisePolicy default (or its
    # TK8S_SUPERVISE_* env override)"; an explicit flag always wins.
    parser.add_argument(
        "--interval", type=float, default=None, metavar="SECONDS",
        help="supervise: seconds between reconcile ticks (default 30; "
        "env TK8S_SUPERVISE_INTERVAL)",
    )
    parser.add_argument(
        "--ticks", type=int, default=0, metavar="N",
        help="supervise: run exactly N reconcile ticks then exit "
        "(default 0 = run until SIGTERM/SIGINT; teardown stops a "
        "running supervisor via its pid lockfile)",
    )
    parser.add_argument(
        "--flap-threshold", type=int, default=None, metavar="N",
        help="supervise: consecutive unhealthy snapshots before a slice "
        "is heal-eligible (default 2 — one transient SSH blip or stale "
        "snapshot never triggers a terraform replace)",
    )
    parser.add_argument(
        "--heal-burst", type=int, default=None, metavar="N",
        help="supervise: per-slice heal token-bucket capacity "
        "(default 2)",
    )
    parser.add_argument(
        "--heal-refill", type=float, default=None, metavar="SECONDS",
        help="supervise: seconds to mint one heal token per slice "
        "(default 600)",
    )
    parser.add_argument(
        "--breaker-threshold", type=int, default=None, metavar="K",
        help="supervise: failed heals within --breaker-window that trip "
        "the global circuit breaker to degraded-hold (default 3)",
    )
    parser.add_argument(
        "--breaker-window", type=float, default=None, metavar="SECONDS",
        help="supervise: sliding window for breaker failures "
        "(default 1800)",
    )
    parser.add_argument(
        "--breaker-cooldown", type=float, default=None, metavar="SECONDS",
        help="supervise: base breaker cooldown before a half-open probe "
        "heal; grows between consecutive trips with the retry engine's "
        "decorrelated jitter (default 300)",
    )
    parser.add_argument(
        "--page-size", type=int, default=None, metavar="N",
        help="supervise: slices per fleet-listing page (default 64 — "
        "sized so one page is one `tpu-vm list` call; a 256-slice fleet "
        "is fetched as bounded pages with per-page TTLs and the retry "
        "classifier's 429 backoff floor instead of one giant ask; "
        "env TK8S_SUPERVISE_PAGE_SIZE)",
    )
    parser.add_argument(
        "--sweep-slices", type=int, default=None, metavar="N",
        help="supervise: slices re-diagnosed per tick beyond the dirty "
        "set (default 4) — the slow full-sweep rotation that bounds how "
        "long listing-invisible drift can hide to "
        "ceil(num_slices/N) ticks (env TK8S_SUPERVISE_SWEEP)",
    )
    parser.add_argument(
        "--heal-workers", type=int, default=None, metavar="N",
        help="supervise: parallel slice-scoped heals per wave (default "
        "8; 1 restores the serial combined heal order) — a zone outage "
        "killing K slices converges in ceil(K/N) heal times "
        "(env TK8S_SUPERVISE_HEAL_WORKERS)",
    )
    parser.add_argument(
        "--domain-threshold", type=int, default=None, metavar="K",
        help="supervise: K slices of one failure domain lost within "
        "--domain-window is classified a DOMAIN_OUTAGE — heals into "
        "that domain are held behind its per-domain breaker and "
        "re-entry is gated by ONE canary heal, while healthy domains "
        "keep healing (default 3; 0 disables the classifier; domains "
        "come from the config's FAILURE_DOMAINS striping; "
        "env TK8S_SUPERVISE_DOMAIN_THRESHOLD)",
    )
    parser.add_argument(
        "--domain-window", type=float, default=None, metavar="SECONDS",
        help="supervise: incident-start span that counts as one "
        "correlated domain failure (default 300; "
        "env TK8S_SUPERVISE_DOMAIN_WINDOW)",
    )
    parser.add_argument(
        "--domain-cooldown", type=float, default=None, metavar="SECONDS",
        help="supervise: base hold before the canary heal re-enters an "
        "outaged domain; grows between re-trips (default 300; "
        "env TK8S_SUPERVISE_DOMAIN_COOLDOWN)",
    )
    parser.add_argument(
        "--quota-defer-cap", type=float, default=None, metavar="SECONDS",
        help="supervise: longest a heal is deferred because its "
        "fleet-listing page is quota-parked (429 backoff floor) — past "
        "this incident age the repair outweighs the API pressure "
        "(default 900; env TK8S_SUPERVISE_QUOTA_DEFER_CAP)",
    )
    parser.add_argument(
        "--compact-records", type=int, default=None, metavar="N",
        help="supervise: auto-compact the event ledger to one snapshot "
        "record once it holds N records (default 20000; 0 disables) — "
        "restart-resume state is preserved exactly "
        "(env TK8S_SUPERVISE_COMPACT)",
    )
    parser.add_argument(
        "--autoscale", action="store_true",
        help="supervise: enable the demand-driven autoscaler — fold "
        "the serving gateway's demand-signal.json into a desired slice "
        "count (hysteresis + cooldown + scale-thrash breaker; "
        "TK8S_AUTOSCALE_* env knobs) and execute it: scale-up through "
        "the warm incremental-provision path, scale-down via "
        "drain-then-teardown with the request journal proving no "
        "accepted request is lost (docs/failure-modes.md, "
        "'Elastic capacity')",
    )
    parser.add_argument(
        "--min-slices", type=int, default=None, metavar="N",
        help="supervise --autoscale: never drain below N slices "
        "(default 1; env TK8S_AUTOSCALE_MIN_SLICES) — pin it when a "
        "workload needs a capacity floor regardless of demand",
    )
    parser.add_argument(
        "--max-slices", type=int, default=None, metavar="N",
        help="supervise --autoscale: never provision past N slices "
        "(default: the config's num_slices envelope; "
        "env TK8S_AUTOSCALE_MAX_SLICES) — pin it to cap spend",
    )
    parser.add_argument(
        "--allocate", action="store_true",
        help="supervise: enable train/serve co-scheduling — the third "
        "controller folds the gateway's demand signal into per-slice "
        "roles (SERVING / TRAINING / TRANSITIONING): idle troughs lend "
        "slices to elastic training, a queue surge preempts them back "
        "through the ledger-recorded PREEMPT_NOTICE -> job-ack -> "
        "ROLE_CHANGED protocol (TK8S_ALLOC_* env knobs; "
        "docs/failure-modes.md, 'Fleet allocation & preemption')",
    )
    parser.add_argument(
        "--train-slices", type=int, default=None, metavar="N",
        help="supervise --allocate: the N highest-index slices start "
        "as the training world (default 0 — training only gets what "
        "idle troughs lend it; env TK8S_ALLOC_TRAIN_SLICES)",
    )
    parser.add_argument(
        "--min-serving", type=int, default=None, metavar="N",
        help="supervise --allocate: never lend serving below N slices "
        "(default 1; env TK8S_ALLOC_MIN_SERVING)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="status: print the raw fleet-status JSON document instead "
        "of the human summary",
    )
    parser.add_argument(
        "--all", action="store_true",
        help="status: include EVERY slice in the per-slice detail "
        "(folded from the event ledger) — the default document stays "
        "bounded at fleet scale: per-state counts plus only the "
        "not-healthy slices",
    )
    # --------------------------------------------------- trace / analyze
    parser.add_argument(
        "--correlate", action="store_true",
        help="analyze: join the supervisor's event ledger with the "
        "request spans and attribute latency-spike windows to fleet "
        "events (heal waves, breaker holds, domain outages)",
    )
    parser.add_argument(
        "--window", type=float, default=60.0, metavar="SECONDS",
        help="analyze --correlate: latency-window width for spike "
        "detection (default 60)",
    )
    # ---------------------------------------------------------- train drill
    parser.add_argument(
        "--steps", type=int, default=200, metavar="N",
        help="train: total optimizer steps for the elastic drill "
        "(default 200)",
    )
    parser.add_argument(
        "--checkpoint-every", type=int, default=25, metavar="N",
        help="train: steps between durable checkpoints — the bound on "
        "work lost to an unplanned membership change (default 25)",
    )
    parser.add_argument(
        "--status-file", type=Path, default=None, metavar="FILE",
        help="train: fleet-status.json to watch (default: the workdir's; "
        "a missing or mid-rewrite file reads as unknown, never healthy)",
    )
    parser.add_argument(
        "--ack-file", type=Path, default=None, metavar="FILE",
        help="train: job-ack.json to write membership acknowledgements "
        "to (default: the workdir's)",
    )
    parser.add_argument(
        "--env-file", type=Path, default=None, metavar="FILE",
        help="train: cluster env file re-read on every rejoin (the "
        "tpuhost role rewrites /etc/tpu-cluster.env with the new "
        "process set after a heal; default: the standard location)",
    )
    parser.add_argument(
        "--max-wait", type=float, default=600.0, metavar="SECONDS",
        help="train: bounded wait for the supervisor's heal before "
        "declaring degraded continuation (default 600)",
    )
    parser.add_argument(
        "--train-report", type=Path, default=None, metavar="FILE",
        help="train: also write the run report (resumes, steps lost, "
        "world size) as JSON to FILE",
    )
    # ----------------------------------------------------- serving gateway
    parser.add_argument(
        "--port", type=int, default=8777, metavar="PORT",
        help="serve: HTTP port for the gateway (default 8777; POST "
        "/generate, GET /healthz)",
    )
    parser.add_argument(
        "--slots", type=int, default=8, metavar="N",
        help="serve: continuous-batching decode slots per engine "
        "(default 8) — requests join the running batch at step "
        "boundaries instead of waiting for it to drain",
    )
    parser.add_argument(
        "--prefill-chunk", type=int, default=32, metavar="TOKENS",
        help="serve: prompt tokens advanced per step boundary (default "
        "32) — one bounded chunk rides along each decode step so long "
        "prompts never stall decoding peers",
    )
    parser.add_argument(
        "--tenant-weights", type=str, default="", metavar="T=W,...",
        help="serve: per-tenant WFQ weights, e.g. 'interactive=3,"
        "batch=1' — claim order becomes weighted fair queueing across "
        "tenants (a flooding tenant is clamped near its weight share "
        "of the queue budget); empty = one homogeneous stream "
        "(docs/failure-modes.md, 'WFQ weight semantics')",
    )
    parser.add_argument(
        "--queue-budget", type=int, default=64, metavar="N",
        help="serve: queued requests before the gateway sheds with a "
        "429-style retry-after (the SLO budget; default 64)",
    )
    parser.add_argument(
        "--drill", type=int, default=0, metavar="N",
        help="serve: run N seeded requests through the gateway+engine "
        "path and print a JSON report instead of listening on --port "
        "(the no-network smoke)",
    )
    parser.add_argument(
        "--serve-report", type=Path, default=None, metavar="FILE",
        help="serve --drill: also write the JSON report to FILE",
    )
    parser.add_argument(
        "--deadline", type=float, default=None, metavar="S",
        help="serve: default per-request deadline in seconds (requests "
        "may override with deadline_s; past it the request settles as "
        "a clean 504-class expiry audited with where the time went)",
    )
    parser.add_argument(
        "--allow-no-fleet-view", action="store_true",
        help="serve: admit traffic even before fleet-status.json has "
        "ever been read (default: shed no-fleet-view 429s on cold "
        "start until the supervisor publishes a view)",
    )
    parser.add_argument(
        "--kv-page-size", type=int, default=16, metavar="TOKENS",
        help="serve: KV-cache page size in tokens (paged slots: a "
        "request holds ceil(span/page_size) pages instead of a dense "
        "max_len row, and shared prompt prefixes are shared pages)",
    )
    parser.add_argument(
        "--kv-pages", type=int, default=0, metavar="N",
        help="serve: total KV pages in the engine's pool (0 = "
        "memory-equal to the dense cache: slots * ceil(max_len / "
        "page_size)) — raise it to cache more shared prefixes",
    )
    parser.add_argument(
        "--no-prefix-cache", action="store_true",
        help="serve: disable cross-request prefix/KV reuse (every "
        "request re-prefills its whole prompt — the pre-hot-path "
        "behavior, kept as an A/B lever)",
    )
    parser.add_argument(
        "--draft-model", type=str, default="tiny",
        choices=("tiny", "none"),
        help="serve: drafter config for speculative decoding (a "
        "smaller models/ TransformerLM proposing --spec-k tokens per "
        "round; the target verifies them in one batched forward with "
        "exact accept/reject, so greedy output stays token-identical). "
        "'none' disables, same as --no-spec",
    )
    parser.add_argument(
        "--spec-k", type=int, default=4, metavar="K",
        help="serve: drafter tokens proposed per speculative round "
        "(default 4) — tokens-per-target-step multiplies by the "
        "acceptance length; 0 disables speculation",
    )
    parser.add_argument(
        "--no-spec", action="store_true",
        help="serve: disable speculative decoding (one target decode "
        "step per token — the pre-spec behavior, kept as an A/B "
        "lever)",
    )
    parser.add_argument(
        "--config",
        type=Path,
        default=None,
        help="load config from file instead of the interactive wizard",
    )
    parser.add_argument(
        "--workdir",
        type=Path,
        default=Path.cwd(),
        help="repo root holding terraform/ and ansible/ (default: cwd)",
    )
    parser.add_argument(
        "--skip-readiness",
        action="store_true",
        help="do not wait for the cluster to become ready",
    )
    parser.add_argument(
        "--fault-plan",
        default=None,
        metavar="PLAN",
        help="deterministic fault-injection plan (inline JSON or a file "
        "path; also read from TK8S_FAULT_PLAN): fail the Nth child "
        "command matching a pattern with a chosen exit code/output/hang "
        "— chaos drills and retry-path tests (docs/failure-modes.md)",
    )
    parser.add_argument(
        "--readiness-timeout", type=float, default=900.0, metavar="SECONDS"
    )
    parser.add_argument(
        "--probe",
        action="store_true",
        help="gke mode: after readiness, run the TPU probe Job "
        "(workload-level JAX device acceptance test)",
    )
    parser.add_argument(
        "--probe-image",
        default=None,
        metavar="IMAGE",
        help="container image for the probe Job (default: plain python; "
        "the probe self-installs pinned jax[tpu])",
    )
    parser.add_argument(
        "--bench-image",
        default=os.environ.get("BENCH_IMAGE") or None,
        metavar="IMAGE",
        help="container image for the generated benchmark Job (default: "
        "plain python + self-install of the framework from a ConfigMap; "
        "build a custom image with the repo Dockerfile). Also read from "
        "the BENCH_IMAGE environment variable.",
    )
    parser.add_argument(
        "--checkpoint-dir",
        default=os.environ.get("TK8S_CHECKPOINT_DIR") or None,
        metavar="DIR",
        help="checkpoint directory for the generated benchmark Job — use a "
        "gs:// bucket so checkpoints survive pod restarts (cross-slice "
        "training shares DIR; with --independent-slices each slice "
        "writes DIR/slice-N). Also read from TK8S_CHECKPOINT_DIR.",
    )
    parser.add_argument(
        "--bench-workload",
        choices=sorted(compiler.BENCH_WORKLOADS),
        default=os.environ.get("TK8S_BENCH_WORKLOAD") or "resnet50",
        help="benchmark family for the generated Job: resnet50 (the "
        "flagship), vit (transformer vision), lm (long-context "
        "Transformer — combine with --bench-flags for ring/MoE/pipeline "
        "parallelism), or decode (KV-cache serving throughput). Also "
        "read from TK8S_BENCH_WORKLOAD.",
    )
    parser.add_argument(
        "--bench-flags",
        default=os.environ.get("TK8S_BENCH_FLAGS") or "",
        metavar="FLAGS",
        help="extra flags appended to the benchmark Job's module "
        "invocation, shell-style (e.g. \"--sequence-parallelism 4\" or "
        "\"--moe-experts 8 --expert-parallelism 4\"). Also read from "
        "TK8S_BENCH_FLAGS.",
    )
    parser.add_argument(
        "--workload-image",
        default=None,
        metavar="IMAGE",
        help="also compile a bring-your-own workload Job per slice for "
        "this container image (same coordinator/topology wiring as the "
        "benchmark Job; docs/detailed.md section 2b)",
    )
    parser.add_argument(
        "--workload-command",
        default=None,
        metavar="CMD",
        help='command line for --workload-image, one shell-style string '
        '(e.g. "python train.py --steps 10000")',
    )
    parser.add_argument(
        "--workload-name",
        default="workload",
        metavar="NAME",
        help="Job/Service name prefix for --workload-image manifests",
    )
    parser.add_argument(
        "--resize",
        type=int,
        default=None,
        metavar="N",
        help="change the deployment to N slices and reconverge: terraform "
        "adds/removes slice node pools (or TPU VMs), ansible reconverges "
        "hosts, manifests recompile with the new cross-slice topology. "
        "Requires a previous run (the saved config is updated). With "
        "cross-slice training and --checkpoint-dir, the re-deployed "
        "workload resumes from the shared checkpoint at the new "
        "data-parallel width.",
    )
    parser.add_argument(
        "--independent-slices",
        action="store_true",
        help="with num_slices > 1, compile each slice's Jobs as an "
        "independent JAX cluster (the pre-r5 behavior) instead of the "
        "default single cross-slice training surface spanning all "
        "slices over DCN (docs/parallelism.md)",
    )
    parser.add_argument(
        "--show-config",
        action="store_true",
        help="print the resolved configuration and exit (no provisioning)",
    )
    return parser


def main(argv: list[str] | None = None, prompter: Prompter | None = None) -> int:
    args = build_parser().parse_args(argv)
    prompter = prompter or Prompter()
    paths = state.RunPaths(args.workdir)
    try:
        if args.clean:
            return clean(args, paths, prompter)
        if args.command == "heal":
            return heal_cmd(args, paths, prompter)
        if args.command == "supervise":
            return supervise_cmd(args, paths, prompter)
        if args.command == "status":
            return status_cmd(args, paths, prompter)
        if args.command == "train":
            return train_cmd(args, paths, prompter)
        if args.command == "serve":
            return serve_cmd(args, paths, prompter)
        if args.command == "trace":
            return trace_cmd(args, paths, prompter)
        if args.command == "analyze":
            return analyze_cmd(args, paths, prompter)
        if args.show_config:
            return show_config(args, paths, prompter)
        return provision(args, paths, prompter)
    except (
        ConfigError,
        discovery.DiscoveryError,
        state.MissingStateError,
        readiness.NotReadyError,
        run_mod.CommandError,
        faults.FaultPlanError,
        journal_mod.JournalError,
        events_mod.EventLedgerError,
        supervisor_mod.SupervisorError,
        EndOfInput,
    ) as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        print("\nInterrupted; nothing further was changed. "
              "Re-run ./setup.sh to resume or ./setup.sh -c to clean up.",
              file=sys.stderr)
        return 130
    except BrokenPipeError:
        # stdout consumer (e.g. `| head`) went away; not an error of ours
        return 0


def show_config(args, paths: state.RunPaths, prompter: Prompter) -> int:
    """The debugVars analogue (reference setup.sh:522-531) — but wired up."""
    source = args.config or paths.config_file
    if not source.exists():
        prompter.say(f"No configuration found at {source}.")
        return 1
    config = store.load_config_file(source)
    prompter.say(f"Configuration from {source}:")
    for label, value in wizard.config_rows(config):
        prompter.say(f"  {label:<24} {value}")
    return 0


def build_runners(
    fault_plan_spec: str | None,
    timer: PhaseTimer | None = None,
) -> tuple[run_mod.RunFn, run_mod.RunFn]:
    """Compose the shared (streaming, quiet) RunFn stack for a run:
    fault injection innermost — so injected faults exercise exactly the
    classify/backoff path real ones take — then the retry engine, which
    reports retried attempts into the open phase's runlog record. The
    policy comes from TK8S_RETRY_* / TK8S_ATTEMPT_TIMEOUT env knobs
    (docs/failure-modes.md lists the defaults)."""
    stream: run_mod.RunFn = run_mod.run_streaming
    quiet: run_mod.RunFn = run_mod.run_capture
    plan = faults.load_fault_plan(fault_plan_spec)
    if plan is not None:
        stream, quiet = plan.wrap(stream), plan.wrap(quiet)
    policy = retry.RetryPolicy.from_env()
    record = timer.note_retry if timer is not None else None
    return (
        retry.retrying_runner(stream, policy, record=record),
        retry.retrying_runner(quiet, policy, record=record),
    )


def clean(args, paths: state.RunPaths, prompter: Prompter) -> int:
    if paths.config_file.exists():
        config = store.load_config_file(paths.config_file)
    elif terraform_mod.modes_with_state(paths) or paths.hosts_file.exists():
        # Config gone but terraform state remains (partial manual cleanup):
        # resources must not leak just because `config` was deleted — the
        # reference's cleanRunner keyed off state files (setup.sh:484-521).
        config = None
    else:
        prompter.say("No config or terraform state found — nothing to clean.")
        return 0
    run, _ = build_runners(args.fault_plan)
    ok = teardown.clean(config, paths, prompter, run=run, assume_yes=args.yes)
    return 0 if ok else 1


def heal_cmd(args, paths: state.RunPaths, prompter: Prompter) -> int:
    """`./setup.sh heal [--max-degraded N]` — slice-granular repair of an
    existing deployment (provision/heal.py). Works from the saved config
    (or an explicit --config): heal converges what provision recorded, it
    never invents a new deployment."""
    source = args.config or paths.config_file
    if not source.exists():
        raise state.MissingStateError(
            f"no configuration at {source} — heal repairs an existing "
            "deployment; run ./setup.sh to provision first"
        )
    config = store.load_config_file(source)
    config.validate()
    timer = PhaseTimer(logfile=paths.runlog)
    run, run_quiet = build_runners(args.fault_plan, timer)
    ssh_key: Path | str = ""
    ssh_user = ""
    if config.mode == "tpu-vm":
        ssh_key = discovery.find_ssh_key()
        ssh_user = discovery.ssh_username()
    heal_mod.heal(
        config, paths, prompter,
        run=run, run_quiet=run_quiet,
        ssh_key=str(ssh_key), ssh_user=ssh_user,
        max_degraded=max(0, args.max_degraded),
        readiness_timeout=args.readiness_timeout,
        timer=timer,
    )
    timer.report()
    return 0


def supervise_policy_from_args(args) -> supervisor_mod.SupervisePolicy:
    """TK8S_SUPERVISE_* env defaults, overridden by explicit flags."""
    policy = supervisor_mod.SupervisePolicy.from_env()
    overrides = {
        "interval": args.interval,
        "flap_threshold": args.flap_threshold,
        "heal_burst": args.heal_burst,
        "heal_refill_s": args.heal_refill,
        "breaker_threshold": args.breaker_threshold,
        "breaker_window_s": args.breaker_window,
        "breaker_cooldown_s": args.breaker_cooldown,
        "max_degraded": max(0, args.max_degraded) or None,
        "page_size": args.page_size,
        "sweep_slices": args.sweep_slices,
        "heal_workers": args.heal_workers,
        "compact_records": args.compact_records,
        "domain_threshold": args.domain_threshold,
        "domain_window_s": args.domain_window,
        "domain_cooldown_s": args.domain_cooldown,
        "quota_defer_cap_s": args.quota_defer_cap,
    }
    for field, value in overrides.items():
        if value is not None:
            setattr(policy, field, value)
    return policy


def supervise_cmd(args, paths: state.RunPaths, prompter: Prompter) -> int:
    """`./setup.sh supervise` — the resident reconcile loop
    (provision/supervisor.py): each tick diagnoses the fleet and drives
    it back to spec through the slice-scoped heal path, governed by the
    flap filter, the per-slice heal rate limiter, and the global circuit
    breaker; every observation/verdict/heal/breaker transition lands in
    the durable event ledger, and fleet-status.json is rewritten
    atomically for scrapers. Runs until SIGTERM/SIGINT (or --ticks N);
    teardown stops it via the pid lockfile."""
    source = args.config or paths.config_file
    if not source.exists():
        raise state.MissingStateError(
            f"no configuration at {source} — supervise watches an "
            "existing deployment; run ./setup.sh to provision first"
        )
    config = store.load_config_file(source)
    config.validate()
    timer = PhaseTimer(logfile=paths.runlog)
    run, run_quiet = build_runners(args.fault_plan, timer)
    ssh_key: Path | str = ""
    ssh_user = ""
    if config.mode == "tpu-vm":
        ssh_key = discovery.find_ssh_key()
        ssh_user = discovery.ssh_username()
    from tritonk8ssupervisor_tpu import obs as obs_mod

    autoscaler = None
    if args.autoscale:
        from tritonk8ssupervisor_tpu.provision import (
            autoscale as autoscale_mod,
        )

        autoscale_policy = autoscale_mod.AutoscalePolicy.from_env()
        if args.min_slices is not None:
            autoscale_policy.min_slices = max(1, args.min_slices)
        if args.max_slices is not None:
            autoscale_policy.max_slices = max(1, args.max_slices)
        autoscaler = autoscale_mod.Autoscaler(
            autoscale_policy, envelope=config.num_slices
        )
    allocator = None
    if args.allocate:
        from tritonk8ssupervisor_tpu.provision import (
            allocator as allocator_mod,
        )

        alloc_policy = allocator_mod.AllocatorPolicy.from_env()
        if args.train_slices is not None:
            alloc_policy.train_slices = max(0, args.train_slices)
        if args.min_serving is not None:
            alloc_policy.min_serving = max(1, args.min_serving)
        allocator = allocator_mod.Allocator(
            alloc_policy, envelope=config.num_slices
        )
    sup = supervisor_mod.Supervisor(
        config, paths, prompter,
        run=run, run_quiet=run_quiet,
        policy=supervise_policy_from_args(args),
        ssh_key=str(ssh_key), ssh_user=ssh_user,
        timer=timer,
        readiness_timeout=args.readiness_timeout,
        autoscaler=autoscaler,
        allocator=allocator,
        # tick/diagnose/heal-wave spans + the /metrics-shaped registry,
        # snapshotted to metrics.json every tick (docs/observability.md)
        telemetry=obs_mod.Telemetry.for_run(
            paths, plane=obs_mod.SUPERVISOR,
            echo=lambda line: prompter.say(line),
        ),
    )
    # a signalled stop finishes the current tick, appends supervisor-stop,
    # and releases the pid lock — what teardown's SIGTERM relies on
    try:
        signal.signal(signal.SIGTERM, lambda *_: sup.request_stop())
    except ValueError:
        pass  # not the main thread (tests): --ticks bounds the loop
    return sup.run(ticks=max(0, args.ticks))


def status_cmd(args, paths: state.RunPaths, prompter: Prompter) -> int:
    """`./setup.sh status [--json]` — the machine-readable fleet status.
    Prefers the atomically rewritten fleet-status.json (cheap, what
    scrapers poll); falls back to folding the event ledger when the
    status file is missing (e.g. the supervisor died before its first
    publish). Exit code 0 = healthy, 2 = degraded/holding."""
    import json as json_mod
    import time as time_mod

    # Tolerant read: a missing OR unreadable status file is "unknown,
    # retry" — the atomic rewrite makes torn reads near-impossible, but
    # a half-copied file (rsync, scraper snapshot) must fall back to the
    # ledger fold, never crash or read as healthy.
    doc = None
    if paths.fleet_status.exists() and not args.all:
        try:
            doc = json_mod.loads(paths.fleet_status.read_text())
        except ValueError:
            prompter.say(
                f"NOTE: {paths.fleet_status} is unreadable (torn copy?); "
                "falling back to the event ledger"
            )
    if not isinstance(doc, dict):
        doc = None
    if doc is None and paths.events.exists():
        # --all re-folds the ledger: fleet-status.json is deliberately
        # BOUNDED (counts + not-healthy details), the full per-slice
        # dump only exists on demand
        ledger = events_mod.EventLedger(paths.events)
        doc = events_mod.fleet_status(
            events_mod.fold(ledger.replay()), time_mod.time(),
            all_slices=args.all,
        )
    if doc is None and args.all and paths.fleet_status.exists():
        # --all without a ledger: the bounded document is all there is
        try:
            doc = json_mod.loads(paths.fleet_status.read_text())
        except ValueError:
            doc = None
    if not isinstance(doc, dict):
        doc = None
    if doc is None:
        raise state.MissingStateError(
            f"no fleet status at {paths.fleet_status} and no event "
            f"ledger at {paths.events} — run ./setup.sh supervise to "
            "start the reconcile loop"
        )
    if "telemetry" not in doc:
        # a ledger fold (or a pre-telemetry status file) carries no
        # telemetry block; synthesize one from the on-disk artifacts so
        # `status --json` always answers "where do I scrape"
        from tritonk8ssupervisor_tpu.obs import metrics as metrics_mod

        last_tick = None
        if paths.metrics_snapshot.exists():
            try:
                snap = json_mod.loads(paths.metrics_snapshot.read_text())
                last_tick = metrics_mod.gauge_value(
                    snap, "supervisor_last_tick_seconds"
                )
            except ValueError:
                pass  # torn copy: the pointer is still worth printing
        try:
            span_bytes = paths.span_log.stat().st_size
        except OSError:
            span_bytes = None
        doc["telemetry"] = {
            "metrics_snapshot": (str(paths.metrics_snapshot)
                                 if paths.metrics_snapshot.exists()
                                 else None),
            "span_log": (str(paths.span_log)
                         if paths.span_log.exists() else None),
            "span_log_bytes": span_bytes,
            "last_tick_s": last_tick,
        }
    fleet_block = doc.get("gateway_fleet")
    if (isinstance(fleet_block, dict)
            and fleet_block.get("stalest_demand_age_s") is None):
        # a ledger fold (or a supervisor without a live demand fold)
        # leaves the staleness slot empty; fill it from the on-disk
        # shards' mtimes — wall clock, because mtimes are wall clock,
        # NOT the supervisor's monotonic timeline
        ages = []
        for shard in paths.demand_signals():
            try:
                ages.append(time_mod.time() - shard.stat().st_mtime)
            except OSError:
                continue  # scrubbed between glob and stat: not stale
        if ages:
            fleet_block["stalest_demand_age_s"] = round(max(0.0,
                                                            *ages), 3)
    if args.json:
        prompter.say(json_mod.dumps(doc, indent=2, sort_keys=True))
    else:
        sup = doc.get("supervisor", {})
        prompter.say(f"fleet: {doc.get('verdict', 'unknown')}")
        running = "running" if sup.get("running") else "stopped"
        uptime = sup.get("uptime_s")
        prompter.say(
            f"supervisor: {running}"
            + (f" (pid {sup.get('pid')}, up {uptime:.0f}s, "
               f"{sup.get('ticks', 0)} ticks)"
               if sup.get("running") and uptime is not None else "")
        )
        counts = doc.get("slice_states") or {}
        if counts:
            total = doc.get("slices_total", sum(counts.values()))
            summary = ", ".join(f"{n} {state}"
                                for state, n in sorted(counts.items()))
            prompter.say(f"slices: {summary} (of {total})")
        for index, entry in sorted(doc.get("slices", {}).items(),
                                   key=lambda kv: int(kv[0])):
            detail = f" ({entry['detail']})" if entry.get("detail") else ""
            prompter.say(f"  slice {index}: {entry.get('state')}{detail}")
        heals = doc.get("heals", {})
        prompter.say(
            f"heals: {heals.get('succeeded', 0)}/"
            f"{heals.get('attempted', 0)} succeeded, "
            f"{heals.get('failed', 0)} failed, "
            f"{heals.get('rate_limited', 0)} rate-limited"
        )
        mttr = doc.get("mttr_s", {})
        if mttr.get("count"):
            prompter.say(
                f"mttr: mean {mttr['mean']:.0f}s over {mttr['count']} "
                f"incident(s) (last {mttr['last']:.0f}s)"
            )
        breaker = doc.get("breaker", {})
        prompter.say(
            f"breaker: {breaker.get('state', 'closed')}"
            + (f" (reopen at {breaker.get('reopen_at'):.0f})"
               if breaker.get("reopen_at") else "")
        )
        domains = doc.get("domains") or {}
        if domains or doc.get("domain_outages"):
            open_domains = sorted(
                name for name, entry in domains.items()
                if entry.get("breaker", "closed") != "closed"
            )
            active = sorted(
                name for name, entry in domains.items()
                if entry.get("outage_active")
            )
            prompter.say(
                f"domains: {doc.get('domain_outages', 0)} outage(s) on "
                f"record across {len(domains)} tracked domain(s)"
                + (f"; breaker open: {', '.join(open_domains)}"
                   if open_domains else "")
                + (f"; outage active: {', '.join(active)}"
                   if active else "")
            )
        autoscale = doc.get("autoscale") or {}
        if autoscale.get("enabled"):
            last = autoscale.get("last_decision") or {}
            breaker_as = autoscale.get("breaker") or {}
            cooldown = autoscale.get("cooldown_remaining_s")
            in_progress = autoscale.get("in_progress")
            prompter.say(
                f"autoscale: desired {autoscale.get('desired')} / "
                f"actual {autoscale.get('actual')}"
                + (f", scaling {in_progress.get('direction')} "
                   f"{in_progress.get('slices')}"
                   if in_progress else "")
                + (f", last {last.get('direction')} "
                   f"{last.get('from_count')}->{last.get('to_count')} "
                   f"({last.get('reason')})" if last else "")
                + f", breaker {breaker_as.get('state', 'closed')}"
                + (f", cooldown {cooldown:.0f}s"
                   if cooldown else "")
            )
        allocation = doc.get("allocation") or {}
        if allocation.get("enabled"):
            roles = allocation.get("roles") or {}
            last = allocation.get("last_decision") or {}
            in_progress = allocation.get("in_progress")
            handovers = allocation.get("handovers") or {}
            prompter.say(
                f"allocation: {roles.get('serving', 0)} serving / "
                f"{roles.get('training', 0)} training"
                + (f" / {roles.get('transitioning', 0)} transitioning"
                   if roles.get("transitioning") else "")
                + (f" (training slices "
                   f"{allocation.get('training')})"
                   if allocation.get("training") else "")
                + (f", handover {in_progress.get('direction')} "
                   f"{in_progress.get('slices')}"
                   f"{' acked' if in_progress.get('acked') else ''}"
                   if in_progress else "")
                + (f", last {last.get('direction')} x{last.get('count')}"
                   f" ({last.get('reason')})" if last else "")
                + (f", {handovers.get('forced', 0)} forced"
                   if handovers.get("forced") else "")
            )
        fleet = doc.get("gateway_fleet") or {}
        if fleet:
            stale = fleet.get("stalest_demand_age_s")
            prompter.say(
                f"gateway fleet: {len(fleet.get('replicas') or [])} "
                f"replica(s), {fleet.get('leases_total', 0)} lease(s) "
                f"(epoch {fleet.get('lease_epoch', 0)}; "
                f"{fleet.get('grants', 0)} granted, "
                f"{fleet.get('renews', 0)} renewed, "
                f"{fleet.get('expiries', 0)} expired, "
                f"{fleet.get('revokes', 0)} revoked)"
                + (f", stalest demand signal {stale:.0f}s"
                   if stale is not None else "")
            )
        membership = doc.get("membership", {})
        if membership:
            prompter.say(
                f"membership: generation {membership.get('generation')}"
                + (", heal in progress"
                   if membership.get("heal_in_progress") else "")
                + (f", draining {membership.get('draining')}"
                   if membership.get("draining") else "")
            )
        tel = doc.get("telemetry") or {}
        if tel.get("metrics_snapshot") or tel.get("span_log"):
            last_tick = tel.get("last_tick_s")
            span_bytes = tel.get("span_log_bytes")
            prompter.say(
                "telemetry: "
                + (f"last tick {last_tick:.3f}s, "
                   if last_tick is not None else "")
                + f"metrics {tel.get('metrics_snapshot') or '(none)'}"
                + (f", spans {tel['span_log']}" if tel.get("span_log")
                   else "")
                + (f" ({span_bytes} B)" if span_bytes is not None else "")
            )
        job = doc.get("job", {})
        if job.get("phase"):
            job_mttr = (job.get("mttr_s") or {}).get("last")
            prompter.say(
                f"job: {job['phase']} (generation "
                f"{job.get('generation')}, step {job.get('step')}"
                + (f", acked degraded {job['acked_degraded']}"
                   if job.get("acked_degraded") else "")
                + (f", job MTTR {job_mttr:.0f}s"
                   if job_mttr is not None else "")
                + ")"
            )
    return 0 if doc.get("verdict") == "healthy" else 2


def train_cmd(args, paths: state.RunPaths, prompter: Prompter) -> int:
    """`./setup.sh train` — the elastic-training drill: a small causal
    LM driven by parallel/elastic.py's ElasticTrainer through the real
    make_lm_train_step machinery, watching this workdir's
    fleet-status.json and acknowledging membership changes through
    job-ack.json. Run it on a provisioned deployment (each host gets
    the cluster env from the tpuhost role) or locally against a
    supervisor (or a test harness) rewriting the status file."""
    import json as json_mod

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tritonk8ssupervisor_tpu.models import TransformerLM
    from tritonk8ssupervisor_tpu.parallel import elastic as elastic_mod
    from tritonk8ssupervisor_tpu.parallel import make_workload_mesh
    from tritonk8ssupervisor_tpu.parallel import train as train_lib
    from tritonk8ssupervisor_tpu.parallel.checkpoint import TrainCheckpointer
    from tritonk8ssupervisor_tpu.parallel.mesh import batch_axes

    if not args.checkpoint_dir:
        raise ConfigError(
            "the elastic train drill needs --checkpoint-dir (or "
            "TK8S_CHECKPOINT_DIR): resume at the new world size IS the "
            "drill, and it resumes from the shared checkpoint"
        )
    batch, seq, vocab = 8, 16, 64

    def setup() -> "elastic_mod.TrainSession":
        mesh = make_workload_mesh()
        model = TransformerLM(
            vocab_size=vocab, num_layers=1, num_heads=2, embed_dim=32,
            max_seq_len=seq, dtype=jnp.float32, logits_dtype=jnp.float32,
        )
        tx = train_lib.default_optimizer(learning_rate=0.05)
        sample = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
        state_, shardings = train_lib.create_train_state(
            model, jax.random.key(0), sample, mesh, tx
        )
        step_fn = train_lib.make_lm_train_step(model, tx, mesh, shardings)
        return elastic_mod.TrainSession(state_, shardings, step_fn, mesh)

    def batch_fn(session, step_index: int) -> tuple:
        # deterministic per-step token grid: every process constructs the
        # same global batch, so resumes are reproducible across worlds
        fill = np.random.default_rng(step_index).integers(
            0, vocab, (batch, seq)
        ).astype(np.int32)
        sharding = NamedSharding(session.mesh, P(batch_axes(session.mesh),
                                                 None))
        tokens = jax.make_array_from_callback(
            (batch, seq), sharding, lambda idx: fill[idx]
        )
        return (tokens,)

    env_file = args.env_file
    trainer = elastic_mod.ElasticTrainer(
        setup,
        batch_fn,
        # factory, not instance: orbax's manager runs JAX computations
        # at construction, which must not precede the cluster join
        checkpoint=elastic_mod.ElasticCheckpoint(
            lambda: TrainCheckpointer(args.checkpoint_dir)
        ),
        health=elastic_mod.FileHealthSource(
            args.status_file or paths.fleet_status
        ),
        policy=elastic_mod.ElasticPolicy(
            checkpoint_every=max(1, args.checkpoint_every),
            max_wait_s=args.max_wait,
            max_degraded=max(0, args.max_degraded),
        ),
        ack=elastic_mod.JobAck(args.ack_file or paths.job_ack),
        # first join: the inherited process env (what the launcher set);
        # every REJOIN re-reads the env file — after a heal the tpuhost
        # role rewrote it with the new process set, while this process's
        # inherited variables still describe the dead world
        rejoin_fn=(lambda: elastic_mod.default_initialize(env_file))
        if env_file is not None else None,
        echo=lambda line: prompter.say(line),
    )
    report = trainer.run(max(1, args.steps))
    if args.train_report:
        state.atomic_write_text(
            args.train_report,
            json_mod.dumps(report, indent=2, sort_keys=True) + "\n",
        )
    prompter.say(
        f"elastic train drill done: steps {report['start_step']} -> "
        f"{report['final_step']} at world size {report.get('world')}, "
        f"{len(report['resumes'])} membership resume(s), "
        f"{report['steps_lost']} step(s) lost, "
        f"{report['drain_flushes']} drain flush(es)"
    )
    return 0


def _parse_tenant_weights(raw: str) -> dict | None:
    """'interactive=3,batch=1' -> {'interactive': 3.0, 'batch': 1.0};
    empty/blank -> None (WFQ off). A malformed entry is a usage error,
    not a silently-dropped tenant."""
    raw = (raw or "").strip()
    if not raw:
        return None
    weights: dict = {}
    for part in raw.split(","):
        name, sep, value = part.partition("=")
        if not sep or not name.strip():
            raise SystemExit(
                f"--tenant-weights: expected TENANT=WEIGHT, got {part!r}"
            )
        try:
            weight = float(value)
        except ValueError:
            raise SystemExit(
                f"--tenant-weights: weight for {name.strip()!r} is not "
                f"a number: {value!r}"
            ) from None
        if weight <= 0:
            raise SystemExit(
                f"--tenant-weights: weight for {name.strip()!r} must "
                f"be positive, got {weight}"
            )
        weights[name.strip()] = weight
    return weights


def serve_cmd(args, paths: state.RunPaths, prompter: Prompter) -> int:
    """`./setup.sh serve` — the continuous-batching inference gateway
    (serving/gateway.py) over the real KV-cache decode stack
    (serving/engine.py on models/decode.py), routed by this workdir's
    fleet-status.json through the shared torn-read-tolerant reader: a
    supervisor publishing degraded-hold sheds this gateway's traffic,
    a draining slice stops taking new work. Default mode listens on
    --port (POST /generate {"tokens": [...], "max_new_tokens": N}; GET
    /healthz is 503 while shedding); `--drill N` runs N seeded requests
    with no network and prints the report — the CI smoke. The drill
    model is a small randomly-initialized TransformerLM (like the
    `train` drill, the machinery is the product, the weights are not);
    serving a trained checkpoint is the same path with restored
    params."""
    import json as json_mod
    import time as time_mod

    import jax
    import jax.numpy as jnp

    from tritonk8ssupervisor_tpu import obs as obs_mod
    from tritonk8ssupervisor_tpu.models import TransformerLM
    from tritonk8ssupervisor_tpu.provision.fleetview import FileHealthSource
    from tritonk8ssupervisor_tpu.serving import engine as engine_mod
    from tritonk8ssupervisor_tpu.serving import gateway as gateway_mod
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod
    from tritonk8ssupervisor_tpu.serving import server as server_mod

    vocab, max_seq = 256, 256
    model = TransformerLM(
        vocab_size=vocab, num_layers=2, num_heads=2, embed_dim=64,
        max_seq_len=max_seq, dtype=jnp.float32, logits_dtype=jnp.float32,
    )
    sample = jax.random.randint(jax.random.key(0), (1, 8), 0, vocab)
    params = model.init(jax.random.key(1), sample, train=False)["params"]
    # speculative decoding: a smaller drafter proposes --spec-k tokens
    # per round, the target verifies them in ONE batched forward with
    # exact accept/reject — greedy serving stays token-identical to
    # plain decode (pinned), only tokens-per-target-step changes
    spec_on = (not args.no_spec and args.spec_k > 0
               and args.draft_model != "none")
    draft_model = draft_params = None
    if spec_on:
        draft_model = TransformerLM(
            vocab_size=vocab, num_layers=1, num_heads=2, embed_dim=32,
            max_seq_len=max_seq, dtype=jnp.float32,
            logits_dtype=jnp.float32,
        )
        draft_params = draft_model.init(
            jax.random.key(2), sample, train=False
        )["params"]
    policy = gateway_mod.GatewayPolicy(
        max_seq_len=max_seq,
        slots_per_slice=max(1, args.slots),
        prefill_chunk=max(1, args.prefill_chunk),
        queue_budget=max(1, args.queue_budget),
        bucket_bounds=(32, 64, 128, max_seq - 32),
        default_deadline_s=args.deadline,
        # a standalone drill has no fleet to take advice from; the HTTP
        # mode fronting a supervised workdir sheds no-fleet-view 429s
        # until the supervisor's first publish (docs/failure-modes.md)
        allow_no_view=bool(args.allow_no_fleet_view or args.drill > 0),
        page_size=max(1, args.kv_page_size),
        pages_per_slice=(args.kv_pages if args.kv_pages > 0 else None),
        prefix_cache=not args.no_prefix_cache,
        tenant_weights=_parse_tenant_weights(args.tenant_weights),
        spec_k=(args.spec_k if spec_on else 0),
    )
    # the telemetry plane (obs/): spans fsync'd to the workdir's span
    # log (they survive a SIGKILL exactly like the request journal),
    # metrics registry scraped by GET /metrics and snapshotted at drill
    # exit. Incarnation = pid, so a restarted gateway's spans are
    # distinguishable in `./setup.sh trace <key>`.
    telemetry = obs_mod.Telemetry.for_run(
        paths, clock=time_mod.monotonic, plane=obs_mod.SERVING,
        incarnation=os.getpid(),
        echo=lambda line: prompter.say(line),
    )
    # one local engine: this process serves as "slice 0" of whatever
    # fleet the status file describes — the per-slice dispatch fan-out
    # is the bench/sim's subject (bench_provision.py --serve); the
    # routing/shed contract is identical either way
    eng = engine_mod.SlotEngine(
        model, params, slots=policy.slots_per_slice, max_len=max_seq,
        prefill_chunk=policy.prefill_chunk,
        page_size=policy.page_size,
        num_pages=policy.pages_per_slice,
        prefix_cache=policy.prefix_cache,
        tracer=telemetry.tracer, slice_index=0,
        draft_model=draft_model, draft_params=draft_params,
        spec_k=policy.spec_k,
    )
    if spec_on:
        prompter.say(
            f"[serve] speculative decoding ON: drafter "
            f"'{args.draft_model}' proposes k={policy.spec_k} tokens "
            "per round, exact accept/reject (--no-spec to disable)"
        )
    gw = gateway_mod.Gateway(
        {0: eng},
        FileHealthSource(args.status_file or paths.fleet_status),
        policy=policy,
        echo=lambda line: prompter.say(line),
        reqlog=reqlog_mod.RequestLog(paths.request_log,
                                     echo=lambda line: prompter.say(line)),
        telemetry=telemetry,
        # the autoscaler's input: queue depth, completion rate, recent
        # p99/sheds, per-slice in-flight — atomically rewritten on the
        # poll cadence (provision/autoscale.py reads it back)
        demand_path=paths.demand_signal,
    )
    # crash-resume: a restarted gateway folds its request journal —
    # incomplete work re-admitted front-of-queue, completed idempotency
    # keys answered from the recorded result (exactly-once)
    gw.recover(time_mod.monotonic())
    if args.drill > 0:
        report = server_mod.run_drill(gw, args.drill, vocab)
        doc = json_mod.dumps(report, indent=2, sort_keys=True)
        prompter.say(doc)
        if args.serve_report:
            state.atomic_write_text(args.serve_report, doc + "\n")
        prompter.say(
            f"serve drill done: {report['completed']}/"
            f"{report['submitted']} completed, "
            f"{report['tokens_generated']} tokens, p50 "
            f"{report['p50_latency_s']:.3f}s"
        )
        spec = (report.get("engine") or {}).get("spec")
        if spec and spec.get("drafted"):
            prompter.say(
                f"speculative: k={spec['spec_k']}, acceptance "
                f"{spec['acceptance_rate']:.0%} ({spec['accepted']}/"
                f"{spec['drafted']} drafted accepted, "
                f"{spec['rolled_back']} rolled back)"
            )
        return 0 if report["completed"] == report["submitted"] else 1
    return server_mod.serve_http(
        gw, "127.0.0.1", args.port, echo=lambda line: prompter.say(line)
    )


def trace_cmd(args, paths: state.RunPaths, prompter: Prompter) -> int:
    """`./setup.sh trace <key>` — one request's end-to-end timeline,
    reconstructed from the span log (obs/trace.py) joined with the
    request journal (serving/reqlog.py) under the idempotency key.
    Works on a crashed workdir (both are durable ledgers); spans carry
    the writer's incarnation, so a request that survived a gateway
    SIGKILL shows records from both gateway lives. Exit 0 when the
    terminal accounting is complete (every acceptance settled exactly
    once), 2 when it has gaps."""
    import json as json_mod

    from tritonk8ssupervisor_tpu.obs import analyze as analyze_mod
    from tritonk8ssupervisor_tpu.obs.trace import SpanLog
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod

    if not args.arg:
        raise state.MissingStateError(
            "trace needs a request idempotency key: ./setup.sh trace "
            "<key> (keys are journaled in serve-requests.jsonl; "
            "./setup.sh analyze lists recent activity)"
        )
    spans = (SpanLog(paths.span_log).spans()
             if paths.span_log.exists() else [])
    req_records = (reqlog_mod.RequestLog(paths.request_log).replay()
                   if paths.request_log.exists() else [])
    if not spans and not req_records:
        raise state.MissingStateError(
            f"no span log at {paths.span_log} and no request journal "
            f"at {paths.request_log} — run ./setup.sh serve (or a "
            "bench/chaos drill) first"
        )
    timeline = analyze_mod.request_timeline(args.arg, spans, req_records)
    if args.json:
        prompter.say(json_mod.dumps(timeline, indent=2, sort_keys=True))
    else:
        for line in analyze_mod.render_timeline(timeline):
            prompter.say(line)
    return 0 if timeline["complete"] else 2


def analyze_cmd(args, paths: state.RunPaths, prompter: Prompter) -> int:
    """`./setup.sh analyze [--correlate]` — the cross-plane telemetry
    summary. The base report counts spans per kind and plane over the
    span log's time range; `--correlate` additionally joins the
    supervisor's event ledger with the request spans and attributes
    latency-spike windows to overlapping fleet events ("p99 window
    t=300-480 overlaps heal-wave span for slice 2")."""
    import json as json_mod

    from tritonk8ssupervisor_tpu.obs import analyze as analyze_mod
    from tritonk8ssupervisor_tpu.obs.trace import SpanLog
    from tritonk8ssupervisor_tpu.provision import events as ev_mod
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod

    spans = (SpanLog(paths.span_log).spans()
             if paths.span_log.exists() else [])
    req_records = (reqlog_mod.RequestLog(paths.request_log).replay()
                   if paths.request_log.exists() else [])
    ledger_records = (ev_mod.EventLedger(paths.events).replay()
                      if paths.events.exists() else [])
    if not spans and not req_records and not ledger_records:
        raise state.MissingStateError(
            f"no telemetry on record ({paths.span_log}, "
            f"{paths.request_log}, {paths.events} all absent) — run "
            "./setup.sh serve or supervise first"
        )
    by_kind: dict = {}
    t_lo = t_hi = None
    for span in spans:
        label = f"{span.get('plane', '?')}/{span.get('span', '?')}"
        by_kind[label] = by_kind.get(label, 0) + 1
        start = span.get("start")
        if start is not None:
            t_lo = start if t_lo is None else min(t_lo, start)
            t_hi = (span.get("end", start) if t_hi is None
                    else max(t_hi, span.get("end", start)))
    doc: dict = {
        "span_log": str(paths.span_log),
        "spans": len(spans),
        "spans_by_kind": dict(sorted(by_kind.items())),
        "span_time_range": ([round(t_lo, 3), round(t_hi, 3)]
                            if t_lo is not None else None),
        "journal_records": len(req_records),
        "ledger_records": len(ledger_records),
    }
    if args.correlate:
        doc["correlate"] = analyze_mod.correlate(
            spans, ledger_records, req_records=req_records,
            window_s=max(1.0, args.window),
        )
    if args.json:
        prompter.say(json_mod.dumps(doc, indent=2, sort_keys=True))
        return 0
    prompter.say(
        f"telemetry: {doc['spans']} span(s) in {doc['span_log']}, "
        f"{doc['journal_records']} request-journal record(s), "
        f"{doc['ledger_records']} supervisor ledger record(s)"
    )
    for label, count in sorted(by_kind.items()):
        prompter.say(f"  {label:<28} {count}")
    if args.correlate:
        cor = doc["correlate"]
        prompter.say(
            f"correlate: {cor['completions']} completion(s), overall "
            f"p50 {cor['overall_p50_s']}s / p99 {cor['overall_p99_s']}s "
            f"over {cor['window_s']:.0f}s windows "
            f"({cor['fleet_intervals']} fleet interval(s) on record)"
        )
        if cor["attributions"]:
            for line in cor["attributions"]:
                prompter.say(f"  {line}")
        else:
            prompter.say(
                "  no latency-spike windows above the threshold — "
                "nothing to attribute"
            )
    return 0


def provision(args, paths: state.RunPaths, prompter: Prompter) -> int:
    # Refuse-if-previous-run guard (setup.sh:14-18, 241-244): a config file
    # means a provision is (or was) in flight; converge or clean first. An
    # explicit --config always wins over the saved one.
    resuming = paths.config_file.exists() and args.config is None
    if args.resize is not None:
        # Elastic resize (SURVEY.md §5): same converging pipeline, new
        # slice count — terraform's declarative count adds/destroys
        # slice pools, ansible reconverges membership, the manifests
        # recompile with the new cross-slice coordinates. Gated on an
        # existing run BEFORE the wizard could prompt: resizing nothing
        # is a typo, not a provision.
        if not (resuming or args.config is not None):
            raise ConfigError(
                "--resize N reconverges an existing deployment; no saved "
                "config found (provision first, then resize)"
            )
        if args.resize < 1:
            raise ConfigError(f"--resize {args.resize}: need >= 1 slice")
    if resuming:
        prompter.say(
            f"Previous run detected ({paths.config_file} exists); "
            "resuming with the saved configuration. Run ./setup.sh -c to start over."
        )
    elif paths.config_file.exists():
        prompter.say(
            f"NOTE: overriding saved {paths.config_file} with --config {args.config}"
        )

    timer = PhaseTimer(logfile=paths.runlog)
    # one composed runner pair (fault injection -> retry/backoff) shared
    # by every phase, so transient-fault handling is uniform end to end
    run, run_quiet = build_runners(args.fault_plan, timer)

    with timer.phase("discover-environment"):
        env = discovery.discover()
        discovery.require_credentials(env)

    if args.config is not None:
        config = store.load_config_file(args.config)
        if not config.project:
            config.project = env.project
        config.validate()
    elif resuming:
        config = store.load_config_file(paths.config_file)
        config.validate()
    else:
        config = wizard.run_wizard(prompter, env=env)

    if args.resize is not None and args.resize != config.num_slices:
        prompter.say(
            f"Resizing: {config.num_slices} -> {args.resize} slice(s)"
        )
        config.num_slices = args.resize
        config.validate()

    # Fail preconditions BEFORE any resources are created — the reference
    # validated its key up front too (setup.sh:231-237). Cheapest first.
    ssh_key: Path | str = ""
    ssh_user = ""
    if config.mode == "tpu-vm":
        if args.probe:
            raise ConfigError(
                "--probe runs a Kubernetes Job and requires mode=gke; "
                "tpu-vm slices get the same acceptance test from the "
                "tpuhost ansible role"
            )
        ssh_key = discovery.find_ssh_key()
        ssh_user = discovery.ssh_username()

    if not args.yes and not wizard.verify_config(config, prompter):
        prompter.say("Aborted; nothing was provisioned.")
        return 1

    store.save_config_file(config, paths.config_file)
    store.export_to_env(config)

    tasks = build_provision_dag(
        args, config, paths, prompter,
        run=run, run_quiet=run_quiet, ssh_key=ssh_key, ssh_user=ssh_user,
    )
    # The durable ledger (provision/journal.py): every task transition is
    # fsync'd, so a SIGKILL'd supervisor resumes the dirty suffix of the
    # DAG instead of starting over. The lock rejects a second concurrent
    # supervisor over the same workdir.
    journal = journal_mod.Journal(paths.journal)
    with journal:
        results = run_dag(
            tasks, max_workers=scheduler_workers(), timer=timer,
            journal=journal,
        )
        # Fully green: fold the append-only ledger down to its verified
        # snapshot so heal cycles and daily converges don't grow it
        # unboundedly. A failed run never reaches here, so the attempt
        # history resume needs is still intact when it matters.
        journal.compact()

    banner(config, results["terraform-apply"], results["compile-manifests"],
           prompter)
    timer.report()
    return 0


def scheduler_workers(environ: dict | None = None) -> int:
    """Pool width for the provision DAG. 8 covers the widest antichain of
    the per-slice pipeline at the default 4-slice ceiling (a readiness
    poll + a converge per slice, with terraform/manifests/host-prep done
    by then); more slices queue harmlessly. TK8S_SCHED_WORKERS=1
    degrades to the old strictly sequential pipeline for debugging."""
    env = os.environ if environ is None else environ
    try:
        return max(1, int(env.get("TK8S_SCHED_WORKERS", "8")))
    except ValueError:
        return 8


def build_provision_dag(
    args,
    config: ClusterConfig,
    paths: state.RunPaths,
    prompter: Prompter,
    run: run_mod.RunFn,
    run_quiet: run_mod.RunFn,
    ssh_key: Path | str = "",
    ssh_user: str = "",
    warm: "cache_mod.WarmCache | None" = None,
) -> list[Task]:
    """The provisioning phases as an explicit dependency graph.

    Edges encode real data/order constraints and nothing else:

    - tpu-vm mode is per-slice pipelined: `readiness-slice-N` (TPU state
      via a shared fleet snapshot, then authenticated SSH — the
      deterministic replacement for the reference's sleep-30 bootstrap,
      terraform/master/main.tf:22) needs only terraform's hosts, and
      `configure-slice-N` (ansible --limit) needs only THAT slice's
      readiness plus the short shared `host-prep` (inventory/vars/key
      patch). Slice 0 configures while slice 3 is still booting; the
      old `host-configuration` barrier waited for the whole fleet;
    - GKE keeps the monolith: the gkejoin play drives gcloud/kubectl
      from the control machine ([LOCAL] group — per-slice --limit has
      no meaning there), and readiness comes after because node
      registration is what the wait observes;
    - compile-manifests needs only the config, so it overlaps the whole
      cloud-facing pipeline (the DAG's free win);
    - the probe Job needs a ready cluster.

    Each task also carries its journal metadata (provision/journal.py):
    an inputs-hash over everything that must dirty it when changed
    (tfvars/config/CLI knobs), the artifact paths whose digests get
    recorded at done-time (tfstate, hosts.json, inventory, manifests),
    and a `restore` that recomputes the task's return value from those
    artifacts when a resume skips it. The probe Job carries none — a
    health check is only meaningful re-run. Independently of the
    journal, compile-manifests and the per-slice converges consult the
    content-addressed warm cache (provision/cache.py) INSIDE their task
    body, so a warm re-run is a no-op even after the ledger is gone.

    Diagram + measured cold-vs-warm numbers: docs/performance.md.
    """
    cfg_fp = dataclasses.asdict(config)  # the config fingerprint
    cache = warm if warm is not None else cache_mod.WarmCache(paths.warm_cache)

    def do_terraform(results: dict) -> state.ClusterHosts:
        if terraform_mod.already_applied(config, paths):
            prompter.say("terraform state present; converging existing deployment")
        return terraform_mod.apply(config, paths, run=run, run_quiet=run_quiet)

    job_kwargs = {"image": args.bench_image} if args.bench_image else {}
    if args.checkpoint_dir:
        job_kwargs["checkpoint_dir"] = args.checkpoint_dir
    if args.bench_workload != "resnet50":
        job_kwargs["workload"] = args.bench_workload
    if args.bench_flags:
        job_kwargs["bench_flags"] = tuple(shlex.split(args.bench_flags))
    if args.workload_image:
        job_kwargs["workload_image"] = args.workload_image
        job_kwargs["workload_command"] = shlex.split(
            args.workload_command or ""
        )
        job_kwargs["workload_name"] = args.workload_name
    if args.independent_slices:
        job_kwargs["cross_slice"] = False

    manifest_key = journal_mod.inputs_hash(
        "compile-manifests", cfg_fp, job_kwargs
    )

    def do_manifests(results: dict) -> list:
        if cache.fresh("compile-manifests", manifest_key,
                       artifacts=(paths.manifests_dir,)):
            prompter.say("  compile-manifests: inputs unchanged "
                         "(warm cache); reusing compiled manifests")
            return sorted(paths.manifests_dir.glob("*.yaml"))
        out = compiler.write_manifests(
            config, paths.manifests_dir, **job_kwargs
        )
        cache.record("compile-manifests", manifest_key,
                     artifacts=(paths.manifests_dir,))
        return out

    def do_probe(results: dict) -> None:
        readiness.run_probe_job(
            config,
            paths.probe_dir,
            run=run,
            run_quiet=run_quiet,
            timeout_seconds=args.readiness_timeout,
            image=args.probe_image,
        )

    tf_task = Task(
        "terraform-apply", do_terraform,
        inputs_hash=journal_mod.inputs_hash(
            "terraform-apply", compiler.to_tfvars(config)
        ),
        artifacts=(paths.tfstate(config.mode), paths.hosts_file),
        restore=lambda results: state.load_hosts(paths),
    )
    manifests_task = Task(
        "compile-manifests", do_manifests,
        inputs_hash=manifest_key,
        artifacts=(paths.manifests_dir,),
        restore=lambda results: sorted(paths.manifests_dir.glob("*.yaml")),
    )
    tasks = [tf_task, manifests_task]

    if config.mode == "tpu-vm":
        tasks += build_slice_pipeline(
            args, config, paths, cache,
            run=run, run_quiet=run_quiet,
            ssh_key=ssh_key, ssh_user=ssh_user, cfg_fp=cfg_fp,
        )
        return tasks

    # ------------------------------------------------------------ gke mode

    def do_ansible(results: dict) -> None:
        ansible_mod.write_runtime_configs(
            config, results["terraform-apply"], paths,
            ssh_key=ssh_key, ansible_user=ssh_user,
        )
        ansible_mod.run_playbook(paths, run=run)

    def do_readiness(results: dict) -> None:
        wait_ready(config, args.readiness_timeout, run_quiet=run_quiet)

    tasks.append(Task(
        "host-configuration", do_ansible, after=("terraform-apply",),
        inputs_hash=journal_mod.inputs_hash(
            "host-configuration", cfg_fp, str(ssh_key), ssh_user
        ),
        artifacts=(paths.inventory, paths.hosts_file),
    ))
    ready_gate = "host-configuration"
    if not args.skip_readiness:
        tasks.append(Task(
            "readiness-wait", do_readiness, after=("host-configuration",),
            inputs_hash=journal_mod.inputs_hash("readiness-wait", cfg_fp),
            artifacts=(paths.hosts_file,),
        ))
        ready_gate = "readiness-wait"
    if args.probe:
        # no journal metadata: the probe is an acceptance test, and a
        # resumed run must re-prove the cluster, not trust a record
        tasks.append(Task("probe-job", do_probe, after=(ready_gate,)))
    return tasks


def build_slice_pipeline(
    args,
    config: ClusterConfig,
    paths: state.RunPaths,
    cache: "cache_mod.WarmCache",
    run: run_mod.RunFn,
    run_quiet: run_mod.RunFn,
    ssh_key: Path | str,
    ssh_user: str,
    cfg_fp: dict,
) -> list[Task]:
    """The tpu-vm per-slice tail of the DAG: one shared `host-prep`
    (runtime configs — seconds of local file writes) plus, per slice, a
    `readiness-slice-N` (shared fleet snapshot + adaptive-backoff polls)
    and a `configure-slice-N` (cache-aware `ansible --limit`). The only
    cross-slice edge is host-prep; each slice's converge starts the
    moment ITS hosts accept authenticated SSH."""
    # one batched `tpu-vm list` per TTL window serves every slice's poll
    snapshot = readiness.FleetSnapshot(config, run_quiet=run_quiet)

    def do_host_prep(results: dict) -> None:
        ansible_mod.write_runtime_configs(
            config, results["terraform-apply"], paths,
            ssh_key=ssh_key, ansible_user=ssh_user,
        )

    tasks = [Task(
        "host-prep", do_host_prep, after=("terraform-apply",),
        inputs_hash=journal_mod.inputs_hash(
            "host-prep", cfg_fp, str(ssh_key), ssh_user
        ),
        artifacts=(paths.inventory,),
    )]

    def slice_readiness_task(i: int) -> Task:
        name = f"readiness-slice-{i}"
        node = f"{config.node_prefix}-{i}"

        def fn(results: dict) -> None:
            # one shared budget for both polls — the user's timeout caps
            # the whole slice's wait, not each poll
            hosts = results["terraform-apply"]
            poll_start = time.monotonic()
            readiness.poll(
                lambda: readiness.tpu_vm_probe(
                    config, [node], run_quiet, snapshot=snapshot
                ),
                timeout=args.readiness_timeout,
                adapt=readiness.AdaptiveInterval(base=5.0, max_interval=45.0),
            )
            remaining = max(
                0.0,
                args.readiness_timeout - (time.monotonic() - poll_start),
            )
            slice_ips = (
                hosts.host_ips[i] if i < len(hosts.host_ips) else []
            )
            readiness.poll(
                lambda: readiness.ssh_ready_probe(
                    slice_ips, ssh_user=ssh_user, ssh_key=str(ssh_key),
                    run_quiet=run_quiet,
                ),
                timeout=remaining,
                adapt=readiness.AdaptiveInterval(base=2.0, max_interval=15.0),
            )

        return Task(
            name, fn, after=("terraform-apply",),
            inputs_hash=journal_mod.inputs_hash(name, cfg_fp),
            artifacts=(paths.hosts_file,),
        )

    def slice_converge_task(i: int, after: tuple) -> Task:
        name = f"configure-slice-{i}"

        def fn(results: dict) -> bool:
            return ansible_mod.converge_slice(
                config, paths, results["terraform-apply"], i,
                run=run, cache=cache,
                ssh_key=ssh_key, ssh_user=ssh_user,
            )

        return Task(
            name, fn, after=after,
            inputs_hash=journal_mod.inputs_hash(
                name, cfg_fp, str(ssh_key), ssh_user
            ),
            artifacts=(paths.inventory,),
        )

    for i in range(config.num_slices):
        converge_after = ["host-prep"]
        if not args.skip_readiness:
            tasks.append(slice_readiness_task(i))
            converge_after.append(f"readiness-slice-{i}")
        tasks.append(slice_converge_task(i, tuple(converge_after)))
    return tasks


def wait_ready(
    config: ClusterConfig,
    timeout: float,
    run_quiet: run_mod.RunFn = run_mod.run_capture,
) -> None:
    if config.mode == "gke":
        readiness.poll(
            lambda: readiness.gke_tpu_probe(config, run_quiet),
            timeout=timeout,
        )
    else:
        names = [
            f"{config.node_prefix}-{i}" for i in range(config.num_slices)
        ]
        readiness.poll(
            lambda: readiness.tpu_vm_probe(config, names, run_quiet),
            timeout=timeout,
        )


def banner(config, hosts: state.ClusterHosts, manifest_paths, prompter: Prompter) -> None:
    """Success banner with the URLs of record — the dashboard/kubectl-config
    URL printout analogue (setup.sh:49-91)."""
    prompter.say("")
    prompter.say("---------------------------------------------------------")
    prompter.say(" Cluster is ready.")
    prompter.say("---------------------------------------------------------")
    if config.mode == "gke":
        prompter.say(
            "  Workloads:  https://console.cloud.google.com/kubernetes/"
            f"workload/overview?project={config.project}"
        )
        prompter.say(
            f"  kubeconfig: gcloud container clusters get-credentials "
            f"{config.cluster_name} --zone {config.zone} --project {config.project}"
        )
        prompter.say(
            f"  Benchmark:  kubectl apply -f {manifest_paths[0].parent}/"
        )
    else:
        for i, slice_ips in enumerate(hosts.host_ips):
            prompter.say(f"  slice {i}: {', '.join(slice_ips)}")
        prompter.say(
            f"  SSH:       gcloud compute tpus tpu-vm ssh {config.node_prefix}-0 "
            f"--zone {config.zone}"
        )
        prompter.say(
            "  Benchmark: python -m tritonk8ssupervisor_tpu.benchmarks.resnet50"
        )


if __name__ == "__main__":
    sys.exit(main())
