"""GCP environment discovery.

The reference bootstrapped credentials by ``eval $(triton env)`` and then
scanned ``~/.ssh`` for the private key whose fingerprint matched
``$SDC_KEY_ID``, hard-failing (with cleanup) when absent
(setConfigFromTritonENV, reference setup.sh:209-239). The TPU/GCP analogue
discovers project/account/zone from ``gcloud config``, verifies credentials
exist, and locates the SSH private key Ansible will use for TPU VMs.

All subprocess execution goes through an injectable runner so tests use a
fake gcloud (SURVEY.md §4: testability designed in, not bolted on).
"""

from __future__ import annotations

import dataclasses
import subprocess
from pathlib import Path
from typing import Callable


class DiscoveryError(RuntimeError):
    """Environment is not usable; message says how to fix it."""


@dataclasses.dataclass
class GcloudEnv:
    """What `gcloud config` knows — the SDC_URL/SDC_ACCOUNT/SDC_KEY_ID
    analogue (reference setup.sh:211-213)."""

    project: str = ""
    account: str = ""
    zone: str = ""


Runner = Callable[..., "subprocess.CompletedProcess[str]"]


def _default_runner(args, **kwargs):
    return subprocess.run(
        args, capture_output=True, text=True, timeout=30, **kwargs
    )


def _gcloud_get(key: str, run: Runner) -> str:
    try:
        proc = run(["gcloud", "config", "get-value", key])
    except (OSError, subprocess.SubprocessError):
        return ""
    if proc.returncode != 0:
        return ""
    value = proc.stdout.strip()
    return "" if value in ("", "(unset)") else value


def discover(run: Runner = _default_runner) -> GcloudEnv:
    """Pull project/account/zone from gcloud config; empty fields mean
    "unknown" and the wizard prompts for them instead.

    The three lookups are independent gcloud invocations (~1 s of CLI
    startup each), so they fan out concurrently — discovery costs one
    gcloud round-trip, not three (the DAG-pipeline discipline applied to
    the pre-wizard phase; docs/performance.md)."""
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
        max_workers=3, thread_name_prefix="gcloud-discover"
    ) as pool:
        project, account, zone = pool.map(
            lambda key: _gcloud_get(key, run),
            ("project", "account", "compute/zone"),
        )
    return GcloudEnv(project=project, account=account, zone=zone)


def require_credentials(env: GcloudEnv, run: Runner = _default_runner) -> None:
    """Hard-fail with guidance when no usable identity exists — the analogue
    of the reference aborting (and cleaning up) when the Triton SSH key was
    missing (setup.sh:231-237)."""
    if env.account:
        return
    try:
        proc = run(["gcloud", "auth", "list", "--format=value(account)"])
        if proc.returncode == 0 and proc.stdout.strip():
            env.account = proc.stdout.strip().splitlines()[0]
            return
    except (OSError, subprocess.SubprocessError):
        pass
    raise DiscoveryError(
        "no GCP credentials found: run `gcloud auth login` and "
        "`gcloud auth application-default login`, then re-run setup.sh"
    )


# Candidate private keys, most specific first. The reference matched keys by
# MD5 fingerprint against $SDC_KEY_ID (setup.sh:215-230); GCP instead
# installs gcloud's own key (or any key in project metadata), so we take the
# first existing candidate and let the operator override.
_SSH_KEY_CANDIDATES = ("google_compute_engine", "id_ed25519", "id_rsa")


def find_ssh_key(ssh_dir: Path | None = None) -> Path:
    """Locate the private key Ansible should use for TPU VM SSH.

    Raises DiscoveryError when none exists, mirroring the reference's
    missing-key abort (setup.sh:231-237).
    """
    ssh_dir = ssh_dir if ssh_dir is not None else Path.home() / ".ssh"
    for name in _SSH_KEY_CANDIDATES:
        candidate = ssh_dir / name
        if candidate.is_file():
            return candidate
    raise DiscoveryError(
        f"no SSH private key found in {ssh_dir} "
        f"(looked for {', '.join(_SSH_KEY_CANDIDATES)}); "
        "run `gcloud compute config-ssh` to create one"
    )


def ssh_username() -> str:
    """The SSH login for TPU VMs. GCP maps metadata/OS-Login SSH keys to
    user accounts and disables direct root login, so the inventory must
    not say root (the reference's VMs accepted root after the key copy,
    reference terraform/master/main.tf:13-27 — GCP works differently).
    `gcloud compute ssh` / `gcloud compute tpus tpu-vm ssh` default to the
    local OS username; TK8S_SSH_USER overrides for OS-Login setups whose
    derived username differs."""
    import getpass
    import os
    import sys

    override = os.environ.get("TK8S_SSH_USER")
    user = override or getpass.getuser()
    if user == "root" and not override:
        # getuser() says root when the CLI itself runs as root (containers,
        # CI) — exactly the login GCP blocks. Don't fail (the play may be
        # targeting a custom image), but make the fix obvious.
        print(
            "warning: derived SSH username is 'root', which GCP TPU VMs "
            "reject by default; set TK8S_SSH_USER to the OS-Login/metadata "
            "username the VMs expect",
            file=sys.stderr,
        )
    return user


def list_networks(project: str = "", run: Runner = _default_runner) -> list[str]:
    """Live VPC network names for the wizard menu — the `triton networks`
    menu analogue (reference setup.sh:257,309-400, default
    Joyent-SDC-Public). Any gcloud failure falls back to ["default"],
    the network every fresh GCP project carries."""
    cmd = ["gcloud", "compute", "networks", "list", "--format=value(name)"]
    if project:
        cmd.append(f"--project={project}")
    try:
        proc = run(cmd)
    except (OSError, subprocess.SubprocessError):
        return ["default"]
    if proc.returncode != 0:
        return ["default"]
    names = [line.strip() for line in proc.stdout.splitlines() if line.strip()]
    return names or ["default"]


def list_subnetworks(
    project: str,
    region: str,
    network: str,
    run: Runner = _default_runner,
) -> list[str]:
    """Subnet names of `network` in `region` (auto-mode VPCs have one per
    region named like the network). Fallback mirrors list_networks."""
    cmd = [
        "gcloud",
        "compute",
        "networks",
        "subnets",
        "list",
        f"--network={network}",
        f"--regions={region}",
        "--format=value(name)",
    ]
    if project:
        cmd.append(f"--project={project}")
    try:
        proc = run(cmd)
    except (OSError, subprocess.SubprocessError):
        return [network or "default"]
    if proc.returncode != 0:
        return [network or "default"]
    names = [line.strip() for line in proc.stdout.splitlines() if line.strip()]
    return names or [network or "default"]


def list_tpu_zones(generation: str, run: Runner = _default_runner) -> list[str]:
    """Zones offering `generation`, live when credentials allow, otherwise
    the static catalog — the same live-with-fallback pattern as the
    reference's `triton networks`/`triton packages` menus (setup.sh:257-259).

    `gcloud compute tpus accelerator-types list` is zone-scoped, so each
    catalog zone is probed individually — but CONCURRENTLY: the probes
    are independent read-only calls, and the wizard's zone menu should
    cost one gcloud round-trip, not len(zones) of them. Any gcloud
    failure falls back to the static catalog.
    """
    from concurrent.futures import ThreadPoolExecutor

    from tritonk8ssupervisor_tpu.config import catalog

    spec = catalog.get_spec(generation)

    def probe_zone(zone: str) -> bool | None:
        """True/False: zone offers the generation; None: gcloud failed."""
        try:
            proc = run(
                [
                    "gcloud",
                    "compute",
                    "tpus",
                    "accelerator-types",
                    "list",
                    f"--zone={zone}",
                    "--format=value(name)",
                ]
            )
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode != 0:
            return None
        # name format: projects/P/locations/ZONE/acceleratorTypes/TYPE
        return any(
            line.split("/")[-1].startswith(spec.type_prefix + "-")
            for line in proc.stdout.strip().splitlines()
        )

    if not spec.zones:
        return []
    with ThreadPoolExecutor(
        max_workers=min(8, len(spec.zones)), thread_name_prefix="gcloud-zones"
    ) as pool:
        verdicts = list(pool.map(probe_zone, spec.zones))
    if any(v is None for v in verdicts):
        return list(spec.zones)
    live = [zone for zone, ok in zip(spec.zones, verdicts) if ok]
    return live or list(spec.zones)
