"""Interactive CLI: prompts, environment discovery, wizard, orchestration.

The TPU-native rebuild of the reference's L0 layer — the `setup.sh` wizard
(reference setup.sh:8-92 `main`, 94-110 `getArgument`, 255-451
`getConfigFromUser`, 452-483 `verifyConfig`).
"""
