"""The interactive configuration wizard.

TPU rebuild of `getConfigFromUser` (reference setup.sh:255-451) and
`verifyConfig` (setup.sh:452-483). The reference prompted for environment
name/description, master hostname, node prefix, node count (1-9), then live
network and KVM-package menus; here the accelerator questions replace the
VM-shape questions — generation, slice topology, slice count, zone — with
menus driven by the accelerator catalog (live-refreshed zones when gcloud
has credentials). Validation is delegated to ClusterConfig.validate()'s
rules so the wizard, file-loaded configs, and tests enforce identical
constraints (unlike the reference, whose regexes lived inline in prompts).
"""

from __future__ import annotations

from tritonk8ssupervisor_tpu.cli import discovery
from tritonk8ssupervisor_tpu.cli.io import Prompter
from tritonk8ssupervisor_tpu.config import catalog
from tritonk8ssupervisor_tpu.config.schema import MAX_SLICES, MODES, ClusterConfig, _NAME_RE


def _name_validator(field: str):
    def check(value: str) -> str:
        if _NAME_RE.match(value):
            return ""
        return (
            f"{field} must be lowercase letters/digits/hyphens, "
            "starting with a letter (RFC1035)"
        )

    return check


def _int_range_validator(lo: int, hi: int, reason: str = ""):
    def check(value: str) -> str:
        try:
            n = int(value)
        except ValueError:
            return f"enter a number {lo}-{hi}"
        if lo <= n <= hi:
            return ""
        return f"must be {lo}-{hi}" + (f" ({reason})" if reason else "")

    return check


def _choose_named(
    prompter: Prompter, title: str, options: list[str], default: str
) -> str:
    """Menu over live-discovered names with an escape hatch for names the
    listing can't see (shared VPCs, cross-project networks) — the
    reference's network menus offered only the listed choices
    (setup.sh:309-400); GCP needs the extra door."""
    other = "other (enter a name)"
    # A configured name the live listing can't see (shared VPC,
    # cross-project) must not silently fall to the first listed option:
    # it joins the menu as its own default-selected entry, so plain
    # Enter preserves the existing config value. The literal "default"
    # is the tool's own schema guess (GCP's auto-network name), not a
    # user choice — unlisted it means "no such network here", so it
    # falls to the first live option as before.
    entries = list(options)
    configured = None
    if default in options:
        default_index = options.index(default)
    elif default and default != "default":
        configured = len(entries)
        entries.append(f"{default} (configured; not in live listing)")
        default_index = configured
    else:
        default_index = 0
    choice = prompter.menu(title, entries + [other], default_index)
    if configured is not None and choice == configured:
        return default
    if choice == len(entries):
        return prompter.ask_validated(
            "Name", default, lambda v: "" if v else "a name is required"
        )
    return options[choice]


def run_wizard(
    prompter: Prompter,
    env: discovery.GcloudEnv | None = None,
    zone_lister=discovery.list_tpu_zones,
    network_lister=discovery.list_networks,
    subnet_lister=discovery.list_subnetworks,
) -> ClusterConfig:
    """Collect a full ClusterConfig interactively.

    Question order mirrors the reference wizard (setup.sh:255-451):
    identity -> naming -> sizing -> placement.
    """
    env = env or discovery.GcloudEnv()
    config = ClusterConfig()

    prompter.say("---------------------------------------------------------")
    prompter.say(" TPU Kubernetes cluster setup")
    prompter.say("---------------------------------------------------------")

    # Identity (the reference read these from `triton env`, setup.sh:209-213)
    config.project = prompter.ask_validated(
        "GCP project",
        env.project,
        lambda v: "" if v else "project is required",
    )

    # Environment metadata (setup.sh:265-271 analogue)
    config.env_name = prompter.ask("Environment name", config.env_name)
    config.env_description = prompter.ask(
        "Environment description", config.env_description
    )

    # Naming (master hostname / node prefix analogues, setup.sh:274-295)
    config.cluster_name = prompter.ask_validated(
        "Cluster name", config.cluster_name, _name_validator("cluster name")
    )
    config.node_prefix = prompter.ask_validated(
        "TPU node name prefix", config.node_prefix, _name_validator("node prefix")
    )

    # Deployment mode: GKE cluster vs standalone TPU VM slice.
    modes = (
        ("gke", "gke     - GKE cluster with a TPU node pool (full Kubernetes)"),
        ("tpu-vm", "tpu-vm  - standalone Cloud TPU VM slice (no Kubernetes)"),
    )
    assert {m for m, _ in modes} == set(MODES)
    config.mode = modes[prompter.menu("Deployment mode:", [l for _, l in modes], 0)][0]

    # Accelerator menus (replace network/package menus, setup.sh:309-450)
    generations = sorted(catalog.ACCELERATORS)
    gen_idx = prompter.menu(
        "TPU generation:",
        [
            f"{g:<4} - up to {catalog.ACCELERATORS[g].max_chips} chips, "
            f"{catalog.ACCELERATORS[g].chips_per_host}/host"
            for g in generations
        ],
        generations.index(catalog.DEFAULT_GENERATION),
    )
    config.generation = generations[gen_idx]
    spec = catalog.ACCELERATORS[config.generation]

    topo_default = (
        spec.topologies.index(catalog.DEFAULT_TOPOLOGY)
        if catalog.DEFAULT_TOPOLOGY in spec.topologies
        else 0
    )
    topo_idx = prompter.menu(
        f"Slice topology ({config.generation}):",
        [
            f"{t:<9} = {spec.topology(t).chips} chips, "
            f"{spec.hosts(spec.topology(t))} host(s)  "
            f"[{catalog.accelerator_type_name(config.generation, t)}]"
            for t in spec.topologies
        ],
        topo_default,
    )
    config.topology = spec.topologies[topo_idx]

    # Slice count keeps the reference's 1-9 guard-rail (setup.sh:297-307).
    # Multiple slices form ONE cross-slice training surface by default
    # (data parallel over DCN, docs/parallelism.md; --independent-slices
    # restores per-slice clusters).
    config.num_slices = int(
        prompter.ask_validated(
            "Number of slices (several = one cross-slice training surface)",
            str(config.num_slices),
            _int_range_validator(1, MAX_SLICES, "no HA support"),
        )
    )

    # Placement (zones with capacity; live list when credentials exist —
    # the `triton networks` live-menu analogue, setup.sh:257)
    zones = zone_lister(config.generation)
    default_zone_idx = zones.index(env.zone) if env.zone in zones else 0
    config.zone = zones[prompter.menu("Zone:", zones, default_zone_idx)]

    # Networking: live menus with defaults, like the reference's `triton
    # networks` menu defaulting to Joyent-SDC-Public (setup.sh:309-400)
    config.network = _choose_named(
        prompter,
        "VPC network:",
        network_lister(config.project),
        config.network,
    )
    config.subnetwork = _choose_named(
        prompter,
        f"VPC subnetwork ({config.region}):",
        subnet_lister(config.project, config.region, config.network),
        config.subnetwork,
    )

    config.validate()
    return config


def config_rows(config: ClusterConfig) -> list[tuple[str, str]]:
    """The summary rows shared by the verify gate and --show-config (the
    debugVars dump analogue, setup.sh:522-531)."""
    rows = [
        ("GCP project", config.project),
        ("Zone", config.zone),
        ("Mode", config.mode),
        ("Cluster name", config.cluster_name),
        ("Environment", f"{config.env_name} - {config.env_description}"),
        ("TPU generation", config.generation),
        ("Slice topology", f"{config.topology} ({config.accelerator_type})"),
        (
            "Slices x hosts x chips",
            f"{config.num_slices} x {config.hosts_per_slice} x "
            f"{config.spec.chips_on_host(config.parsed_topology)}",
        ),
        ("Total chips", str(config.num_slices * config.chips_per_slice)),
        ("Network", f"{config.network} / {config.subnetwork}"),
        ("Runtime version", config.effective_runtime_version),
    ]
    if config.failure_domains > 1:
        rows.append((
            "Failure domains",
            f"{config.failure_domains} (slice i -> "
            f"{config.zone or 'zone'}-fd(i % {config.failure_domains}))",
        ))
    if config.mode == "gke":
        rows.append(("GKE machine type", config.gke_machine_type))
    return rows


def verify_config(config: ClusterConfig, prompter: Prompter) -> bool:
    """Print the full summary and gate on confirmation — verifyConfig
    (setup.sh:452-483), including its reachability warning (setup.sh:468)."""
    prompter.say("")
    prompter.say("Verify the configuration:")
    prompter.say("---------------------------------------------------------")
    for label, value in config_rows(config):
        prompter.say(f"  {label:<24} {value}")
    prompter.say("---------------------------------------------------------")
    prompter.say(
        "NOTE: worker hosts must reach the coordinator over the VPC; "
        "default-network firewall rules usually allow this."
    )
    return prompter.confirm("Proceed with this configuration?")
