"""Compile the validated ClusterConfig into tool-specific artifacts.

The reference did this with string concatenation: generated HCL
(updateTerraformConfig, setup.sh:162-198), an Ansible inventory + vars.yml
(createAnsibleConfigs, setup.sh:116-137), and a sed-patched ansible.cfg
(setup.sh:133). We generate *data* instead of code: a terraform.tfvars.json
consumed by static, reviewable Terraform modules (no HCL codegen), a YAML
inventory, and Kubernetes Job manifests with `google.com/tpu` resource
requests (the benchmark-workload analogue of docs/benchmarks.md).
"""

from __future__ import annotations

import base64
import json
import shlex
from pathlib import Path

import yaml

from tritonk8ssupervisor_tpu import packaging
from tritonk8ssupervisor_tpu.config.schema import ClusterConfig

BENCH_IMAGE_DEFAULT = "python:3.11-slim"
# ConfigMap carrying the framework source archive (packaging.py); mounted
# into the benchmark Job so the default plain-python image can self-install
# the package — no registry required (the probe Job's pattern, extended).
PACKAGE_CONFIGMAP_NAME = "tk8s-pkg"
PACKAGE_MOUNT_PATH = "/opt/tk8s-pkg"


# ---------------------------------------------------------------- terraform


def to_tfvars(config: ClusterConfig) -> dict:
    """Variables for terraform/tpu-vm or terraform/gke root modules.

    Replaces the reference's per-VM `module` block codegen loop
    (setup.sh:145-152) — fan-out lives in HCL `count` now, driven by
    `num_slices` / node counts here.
    """
    common = {
        "project": config.project,
        "zone": config.zone,
        "network": config.network,
        "subnetwork": config.subnetwork,
        "name_prefix": config.node_prefix,
        "num_slices": config.num_slices,
    }
    if config.mode == "tpu-vm":
        return common | {
            "accelerator_type": config.accelerator_type,
            "runtime_version": config.effective_runtime_version,
        }
    return common | {
        "cluster_name": config.cluster_name,
        "machine_type": config.gke_machine_type,
        "tpu_topology": str(config.parsed_topology),
        "nodes_per_slice": config.hosts_per_slice,
        "broad_node_scopes": config.broad_node_scopes,
    }


def write_tfvars(config: ClusterConfig, terraform_dir: Path) -> Path:
    out = terraform_dir / config.mode / "terraform.tfvars.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(to_tfvars(config), indent=2, sort_keys=True) + "\n")
    return out


# ------------------------------------------------------------------ ansible


def _check_slice_shape(name: str, slice_ips) -> None:
    """slice_ips must be per-slice lists (terraform output shape); a flat
    list of strings would silently iterate characters and emit garbage
    host lines."""
    if not isinstance(slice_ips, (list, tuple)) or not all(
        isinstance(s, (list, tuple)) and all(isinstance(ip, str) for ip in s)
        for s in slice_ips
    ):
        raise TypeError(
            f"{name} must be a list of per-slice IP lists "
            f"(e.g. [['10.0.0.1', '10.0.0.2']]), got {slice_ips!r}"
        )


def to_inventory(
    config: ClusterConfig,
    slice_ips: list[list[str]],
    internal_ips: list[list[str]] | None = None,
    ansible_user: str = "",
) -> str:
    """INI inventory, the analogue of the [MASTER]/[HOST] groups the
    reference built from masters.ip/hosts.ip (setup.sh:123-126).

    `slice_ips` (external IPs, SSH addressing) is per-slice (terraform
    output shape): each host line carries its slice index, its position in
    the slice, its slice's coordinator AND the global (slice 0) coordinator
    as inventory hostvars. The tpuhost role writes whichever coordination
    block matches the deployment: multi-slice deployments get the
    cross-slice contract (one jax.distributed cluster spanning all
    slices, global ids computed by parallel/distributed.py — the
    reference joined every node into one compute surface,
    rancherhost/tasks/main.yml:26-34); single-slice multi-host
    deployments get the per-slice contract. Coordinators are first-host
    VPC-internal IPs when `internal_ips` is provided: worker dials to
    an external NAT IP are blocked by default firewall rules, and JAX
    coordinator traffic belongs on the VPC anyway.

    `ansible_user` is the SSH login for TPU VMs (the discovered gcloud
    username — GCP maps metadata/OS-Login keys to user accounts and
    disables direct root SSH; the play escalates with become). Empty means
    omit, letting ansible default to the control machine's user, which is
    what `gcloud compute ssh` would use.

    The [LOCAL] group hosts the gkejoin play, which drives gcloud/kubectl
    from the control machine (the ranchermaster local_action analogue,
    ranchermaster/tasks/main.yml:51-52)."""
    _check_slice_shape("slice_ips", slice_ips)
    if internal_ips:
        _check_slice_shape("internal_ips", internal_ips)
        if [len(s) for s in internal_ips] != [len(s) for s in slice_ips]:
            raise ValueError(
                "internal_ips shape does not match slice_ips: "
                f"{internal_ips!r} vs {slice_ips!r}"
            )
    if (
        config.num_slices > 1
        and slice_ips
        and not slice_ips[0]
        and any(slice_ips[1:])
    ):
        # The cross-slice contract pins the coordinator (global process
        # id 0) to slice 0's first host; without slice 0 no process
        # would run the coordinator service and every other host would
        # hang in jax.distributed.initialize — fail loudly instead.
        raise ValueError(
            "slice 0 has no endpoints but later slices do: the "
            "cross-slice cluster's coordinator lives on slice 0's first "
            "host (re-run provisioning, or drop the empty slice from "
            "the terraform output)"
        )
    lines = ["[TPUHOST]"]
    global_coordinator = ""
    for slice_index, ips in enumerate(slice_ips):
        if not ips:  # slice endpoints not populated (yet) — emit nothing
            continue
        coordinator = (
            internal_ips[slice_index][0] if internal_ips else ips[0]
        )
        if not global_coordinator:
            global_coordinator = coordinator  # slice 0 (guarded above)
        for process_id, ip in enumerate(ips):
            lines.append(
                f"{ip} slice_index={slice_index} process_id={process_id} "
                f"slice_coordinator={coordinator} "
                f"global_coordinator={global_coordinator}"
            )
    lines += ["", "[TPUHOST:vars]"]
    if ansible_user:
        lines.append(f"ansible_user={ansible_user}")
    lines += [
        "ansible_python_interpreter=/usr/bin/python3",
        "",
        "[LOCAL]",
        "localhost ansible_connection=local",
        "",
    ]
    return "\n".join(lines)


def to_ansible_vars(config: ClusterConfig, coordinator_ip: str = "") -> dict:
    """vars.yml analogue (reference setup.sh:128-131 wrote master IP + env
    name/description for the ranchermaster role)."""
    expected_per_host = config.spec.chips_on_host(config.parsed_topology)
    return {
        "coordinator": coordinator_ip,
        "kubernetes_name": config.env_name,
        "kubernetes_description": config.env_description,
        "tpu_generation": config.generation,
        "accelerator_type": config.accelerator_type,
        "runtime_version": config.effective_runtime_version,
        "expected_devices_per_host": expected_per_host,
        "hosts_per_slice": config.hosts_per_slice,
        "num_slices": config.num_slices,
        "expected_total_chips": config.num_slices * config.chips_per_slice,
        # one definition of the acceptance test for both the ansible role
        # and the SSH readiness path (provision/readiness.py)
        "jax_smoke_cmd": jax_smoke_command(expected_per_host),
        # the cluster-wide rendezvous acceptance (r4 verdict weak #4):
        # single-slice deployments must form the slice's JAX cluster,
        # cross-slice deployments the whole surface
        "cluster_smoke_cmd": cluster_smoke_command(
            config.num_slices * config.chips_per_slice
            if config.num_slices > 1 else config.chips_per_slice
        ),
        "project": config.project,
        "zone": config.zone,
        "cluster_name": config.cluster_name,
        "mode": config.mode,
    }


def jax_smoke_command(expected_devices: int) -> str:
    """The per-host acceptance test: JAX must actually see the chips —
    "TPU chips usable" != "VM booted" (SURVEY.md §7 readiness semantics).
    Shared by the tpuhost ansible role (via to_ansible_vars) and the
    tpu-vm SSH readiness path (provision/readiness.py)."""
    return (
        "python3 -c \"import jax; n = jax.local_device_count(); "
        f"assert n == {expected_devices}, "
        f"f'expected {expected_devices} TPU devices, saw {{n}}'; "
        "print(f'JAX OK: {n} devices')\""
    )


def cluster_smoke_command(expected_chips: int, timeout_s: int = 240) -> str:
    """The cluster-wide rendezvous acceptance (r4 verdict weak #4): every
    host runs this CONCURRENTLY after the tpuhost play writes
    /etc/tpu-cluster.env; jax.distributed.initialize must form the
    cluster and the global device count must equal the deployment's chip
    total — the per-host smoke proves "this host's chips are usable",
    this one proves "the hosts form ONE cluster" (the GKE probe Job's
    equivalent for tpu-vm mode). `timeout` bounds a wedged rendezvous
    (e.g. a firewalled coordinator port) so the play fails with the
    assertion context instead of hanging the whole provision."""
    return (
        f"timeout {timeout_s} python3 -c \"import jax; "
        "from tritonk8ssupervisor_tpu.parallel import initialize_from_env; "
        "env = initialize_from_env(); "
        "n = jax.device_count(); "
        f"assert n == {expected_chips}, "
        f"f'expected {expected_chips} cluster chips, saw {{n}}'; "
        "print(f'CLUSTER OK: {jax.process_count()} processes, "
        "{n} chips')\""
    )


def write_ansible_configs(
    config: ClusterConfig,
    slice_ips: list[list[str]],
    ansible_dir: Path,
    coordinator_ip: str = "",
    internal_ips: list[list[str]] | None = None,
    ansible_user: str = "",
) -> None:
    """Generated vars go to group_vars/all.yml so every play sees them (the
    reference funnelled one vars.yml into each play via vars_files,
    clusterUp.yml:12,22)."""
    ansible_dir.mkdir(parents=True, exist_ok=True)
    (ansible_dir / "hosts").write_text(
        to_inventory(
            config, slice_ips, internal_ips=internal_ips, ansible_user=ansible_user
        )
    )
    vars_dir = ansible_dir / "group_vars"
    vars_dir.mkdir(parents=True, exist_ok=True)
    (vars_dir / "all.yml").write_text(
        yaml.safe_dump(to_ansible_vars(config, coordinator_ip), sort_keys=True)
    )
    # Stage the framework source archive for the tpuhost role (files/ is
    # ansible's copy-module search path): every TPU host gets the package
    # installed, so the success banner's advertised benchmark command runs
    # on a fresh VM. Deterministic bytes -> ansible reports changed=false
    # on converge re-runs.
    packaging.build_source_archive(
        ansible_dir / "roles" / "tpuhost" / "files" / packaging.ARCHIVE_NAME
    )


# -------------------------------------------------------------- k8s manifests

# benchmark families deployable as the cluster Job (--bench-workload):
# name -> (module, flags the name implies). "vit" rides the image-
# training harness with its model selector; "decode" is the serving-
# side KV-cache generation benchmark.
BENCH_WORKLOADS = {
    "resnet50": ("tritonk8ssupervisor_tpu.benchmarks.resnet50", ()),
    "vit": ("tritonk8ssupervisor_tpu.benchmarks.resnet50",
            ("--model", "vit")),
    "lm": ("tritonk8ssupervisor_tpu.benchmarks.lm", ()),
    "decode": ("tritonk8ssupervisor_tpu.benchmarks.decode", ()),
}
# workloads whose module accepts --checkpoint-dir (training runs that
# save/resume state; decode generates, nothing to checkpoint)
CHECKPOINTABLE_WORKLOADS = {"resnet50", "vit", "lm"}


def bench_command(module: str = "tritonk8ssupervisor_tpu.benchmarks.resnet50",
                  extra_args: tuple[str, ...] = ("--json",),
                  extra_packages: tuple[str, ...] = ()) -> str:
    """Self-installing benchmark command for the default (plain python)
    image: install the ConfigMap-mounted source archive + the pinned
    jax[tpu], then run the module. This is what makes the generated Job
    runnable as published — the reference's workloads ran straight from
    public images (docs/benchmarks.md:1-4); ours ships its own source.

    extra_args carry user input (e.g. --checkpoint-dir) into a bash -c
    string, so each is shell-quoted; extra_packages join the pip install
    (e.g. gcsfs for gs:// checkpoints)."""
    args = " ".join(shlex.quote(a) for a in extra_args)
    packages = "".join(f" {shlex.quote(p)}" for p in extra_packages)
    return (
        f"pip install --quiet {PACKAGE_MOUNT_PATH}/{packaging.ARCHIVE_NAME} "
        f"'{PROBE_JAX_PIN}'{packages} -f {PROBE_LIBTPU_INDEX} && "
        f"python -m {module} {args}".rstrip()
    )


def to_package_configmap(root: Path | None = None) -> dict:
    """The framework source archive as a ConfigMap (binaryData), mounted by
    the benchmark Job. The archive is deterministic (packaging.py) so this
    manifest is stable across re-runs."""
    blob = packaging.build_archive_bytes(root)
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": PACKAGE_CONFIGMAP_NAME},
        "binaryData": {packaging.ARCHIVE_NAME: base64.b64encode(blob).decode()},
    }


def _slice_job_name(config: ClusterConfig, name: str, slice_index: int) -> str:
    """Indexed-Job pod hostnames are {job_name}-{index}; with num_slices
    > 1 jobs are named {name}-{slice}, so the coordinator address must
    derive from the per-slice job name — each slice forms its own JAX
    cluster (the reference joined each node through its own registration
    URL, rancherhost/tasks/main.yml:19-24)."""
    return f"{name}-{slice_index}" if config.num_slices > 1 else name


def tpu_job_env(
    config: ClusterConfig,
    job_name: str,
    svc: str,
    *,
    name: str | None = None,
    slice_index: int = 0,
    cross_slice: bool | None = None,
) -> list[dict]:
    """The coordinator/topology env wiring every multi-host TPU Job needs
    (the registrationUrl handoff analogue, rancherhost/tasks/main.yml:19-24):
    jax.distributed.initialize reads JAX_*; libtpu's multi-host topology
    discovery reads TPU_WORKER_HOSTNAMES (the full per-pod list — a bare
    service name was the round-2 bug) and TPU_WORKER_ID. Shared by the
    benchmark Job and user-supplied (BYO) workload Jobs so both wire the
    same way.

    cross_slice (default: on whenever num_slices > 1, r4 verdict missing
    #1) joins every slice's Job into ONE jax.distributed cluster — the
    reference joined every provisioned node into one compute surface
    (rancherhost/tasks/main.yml:26-34), and so does this: the coordinator
    is slice 0's pod 0, JAX_NUM_PROCESSES spans all slices, and the
    TK8S_* slice coordinates let parallel/distributed.py compute the
    global process id (a manifest fieldRef cannot do the arithmetic) and
    export libtpu's MEGASCALE_* DCN transport vars at runtime.
    TPU_WORKER_HOSTNAMES stays per-slice either way: it feeds libtpu's
    WITHIN-slice ICI topology discovery; the cross-slice hop is DCN.
    Pass cross_slice=False (CLI --independent-slices) for the r1-r4
    N-independent-clusters behavior."""
    hosts = config.hosts_per_slice
    topo = config.parsed_topology
    if cross_slice is None:
        cross_slice = config.num_slices > 1
    index_ref = {
        "valueFrom": {
            "fieldRef": {
                "fieldPath": "metadata.annotations['batch.kubernetes.io/job-completion-index']"
            }
        }
    }
    if cross_slice and config.num_slices > 1:
        base = name if name is not None else job_name.rsplit("-", 1)[0]
        slice0_job = _slice_job_name(config, base, 0)
        env = [
            {"name": "JAX_COORDINATOR_ADDRESS",
             "value": f"{slice0_job}-0.{svc}:8476"},
            {"name": "JAX_NUM_PROCESSES",
             "value": str(config.num_slices * hosts)},
            {"name": "JAX_PROCESS_ID", **index_ref},
            {"name": "TK8S_NUM_SLICES", "value": str(config.num_slices)},
            {"name": "TK8S_SLICE_ID", "value": str(slice_index)},
            {"name": "TK8S_PROCS_PER_SLICE", "value": str(hosts)},
        ]
    else:
        env = [
            {"name": "JAX_COORDINATOR_ADDRESS",
             "value": f"{job_name}-0.{svc}:8476"},
            {"name": "JAX_NUM_PROCESSES", "value": str(hosts)},
            {"name": "JAX_PROCESS_ID", **index_ref},
        ]
    return env + [
        {"name": "TPU_TOPOLOGY", "value": str(topo)},
        {
            "name": "TPU_WORKER_HOSTNAMES",
            "value": ",".join(f"{job_name}-{i}.{svc}" for i in range(hosts)),
        },
        {"name": "TPU_WORKER_ID", **index_ref},
    ]


def _indexed_tpu_job(
    config: ClusterConfig,
    *,
    name: str,
    job_name: str,
    slice_index: int,
    container: dict,
    backoff_limit: int,
    pod_spec_extra: dict | None = None,
) -> dict:
    """One Indexed Job spanning every host of a slice: one pod per TPU
    host (SPMD — no master/worker asymmetry), nodeSelector pinning to
    the accelerator+topology, google.com/tpu chip accounting via GKE's
    device plugin."""
    topo = config.parsed_topology
    hosts = config.hosts_per_slice
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": job_name,
            "labels": {"app": name, "slice": str(slice_index)},
        },
        "spec": {
            "completions": hosts,
            "parallelism": hosts,
            "completionMode": "Indexed",
            "backoffLimit": backoff_limit,
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "subdomain": f"{name}-svc",
                    "restartPolicy": "Never",
                    "nodeSelector": {
                        "cloud.google.com/gke-tpu-accelerator": _gke_accelerator_label(
                            config.generation
                        ),
                        "cloud.google.com/gke-tpu-topology": str(topo),
                    },
                    "containers": [container],
                    **(pod_spec_extra or {}),
                },
            },
        },
    }


def to_user_workload_job(
    config: ClusterConfig,
    *,
    name: str,
    image: str,
    command: list[str],
    slice_index: int = 0,
    env: dict[str, str] | None = None,
    backoff_limit: int = 0,
    cross_slice: bool | None = None,
) -> dict:
    """A user-supplied (bring-your-own) training/serving container on the
    provisioned TPU pool — the reference's third-party-app walkthrough
    (its docs/detailed.md:255-371 deployed Ghost and Guestbook onto the
    cluster) re-expressed for TPU workloads: your image + command, the
    framework's slice wiring. The container gets the same coordinator/
    topology env and chip requests as the benchmark Job, so any JAX
    program that calls jax.distributed.initialize() (or this package's
    parallel.initialize_from_env) forms the slice's mesh unchanged.

    `env` adds/overrides plain-value variables (e.g. your HF_TOKEN or
    config knobs). manifests/byo-workload.example.yaml shows a rendered
    example; docs/detailed.md §2b is the walkthrough.
    """
    spec = config.spec
    topo = config.parsed_topology
    job_name = _slice_job_name(config, name, slice_index)
    svc = f"{name}-svc"
    env_block = tpu_job_env(config, job_name, svc, name=name,
                            slice_index=slice_index, cross_slice=cross_slice)
    for key, value in (env or {}).items():
        env_block = [e for e in env_block if e["name"] != key]
        env_block.append({"name": key, "value": value})
    chips_on_host = spec.chips_on_host(topo)
    container = {
        "name": "workload",
        "image": image,
        "command": list(command),
        "resources": {
            "requests": {"google.com/tpu": str(chips_on_host)},
            "limits": {"google.com/tpu": str(chips_on_host)},
        },
        "env": env_block,
        "ports": [{"containerPort": 8476}],
    }
    return _indexed_tpu_job(
        config,
        name=name,
        job_name=job_name,
        slice_index=slice_index,
        container=container,
        backoff_limit=backoff_limit,
    )


def to_benchmark_job(
    config: ClusterConfig,
    *,
    name: str = "resnet50-bench",
    image: str = BENCH_IMAGE_DEFAULT,
    command: list[str] | None = None,
    slice_index: int = 0,
    checkpoint_dir: str = "",
    workload: str = "resnet50",
    bench_flags: tuple[str, ...] = (),
    cross_slice: bool | None = None,
) -> dict:
    """Training benchmark as an Indexed Job spanning every host of a slice.

    This is the TPU-native re-expression of the reference's benchmark
    container workload (docs/benchmarks.md:1-4) and its node-join logic
    (rancherhost/tasks/main.yml:26-34): instead of a rancher/agent phoning
    home, K8s schedules one pod per TPU host; the completion index + a
    headless service give jax.distributed.initialize its coordinator.

    `workload` picks the benchmark family ("resnet50" — the flagship —
    or "lm", the long-context Transformer); `bench_flags` append raw
    module flags, which is how the parallelism knobs reach the cluster
    (e.g. ("--sequence-parallelism", "4") or ("--moe-experts", "8",
    "--expert-parallelism", "4") — benchmarks/lm.py validates the
    combinations at startup, so a bad set fails the Job loudly on the
    first pod log line rather than silently running something else).
    """
    if workload not in BENCH_WORKLOADS:
        raise ValueError(
            f"workload={workload!r}: expected one of "
            f"{sorted(BENCH_WORKLOADS)}"
        )
    module, implied_flags = BENCH_WORKLOADS[workload]
    bench_flags = (*implied_flags, *bench_flags)
    if checkpoint_dir and workload not in CHECKPOINTABLE_WORKLOADS:
        # caught here, at manifest compile time, because the module's
        # argparse would otherwise reject --checkpoint-dir on every pod
        # and the Job would burn its whole restart budget on a
        # guaranteed-failing command
        raise ValueError(
            f"--checkpoint-dir is not supported by the {workload!r} "
            f"workload (training workloads only: "
            f"{sorted(CHECKPOINTABLE_WORKLOADS)})"
        )
    spec = config.spec
    topo = config.parsed_topology
    chips_on_host = spec.chips_on_host(topo)
    svc = f"{name}-svc"
    job_name = _slice_job_name(config, name, slice_index)
    # resolve the mode ONCE: the checkpoint layout and the cluster
    # topology env must agree (independent clusters sharing one orbax
    # dir would clobber each other's steps)
    cross_slice = (cross_slice if cross_slice is not None
                   else config.num_slices > 1)
    # Checkpoints need a home that outlives the pod; a gs:// bucket is the
    # durable choice (orbax writes it natively — the node pool's service
    # account needs storage read/write scope, see docs/benchmarks.md).
    if checkpoint_dir and command is not None:
        raise ValueError(
            "checkpoint_dir only applies to the generated benchmark "
            "command; bake the flag into the explicit `command` instead"
        )
    bench_args: tuple[str, ...] = ("--json", *bench_flags)
    extra_packages: tuple[str, ...] = ()
    if checkpoint_dir:
        # Independent slices each train their own state -> per-slice
        # subdirectories so they don't clobber one another. Cross-slice
        # mode trains ONE state across all slices -> one shared dir
        # (orbax's multihost protocol has only process 0 finalize).
        if config.num_slices > 1 and not cross_slice:
            ckpt = checkpoint_dir.rstrip("/") + f"/slice-{slice_index}"
        else:
            ckpt = checkpoint_dir.rstrip("/")
        bench_args += ("--checkpoint-dir", ckpt)
        if checkpoint_dir.startswith("gs://"):
            # orbax's epath needs a GCS backend; plain python pods have
            # none and would crash-loop on the first mkdir (pyproject
            # optional-dependency `gcs`)
            extra_packages = ("gcsfs",)
    # Default path: plain python image + self-install from the package
    # ConfigMap (bench_command). A custom image is assumed to carry the
    # framework already (Dockerfile at the repo root builds one).
    self_install = command is None and image == BENCH_IMAGE_DEFAULT
    if command is None:
        command = (
            ["bash", "-c", bench_command(module=module,
                                         extra_args=bench_args,
                                         extra_packages=extra_packages)]
            if self_install
            else ["python", "-m", module, *bench_args]
        )
    container = {
        "name": "bench",
        "image": image,
        "command": command,
        "resources": {
            "requests": {"google.com/tpu": str(chips_on_host)},
            "limits": {"google.com/tpu": str(chips_on_host)},
        },
        "env": tpu_job_env(config, job_name, svc, name=name,
                           slice_index=slice_index, cross_slice=cross_slice),
        "ports": [{"containerPort": 8476}],
    }
    pod_spec_extra = {}
    if self_install:
        container["volumeMounts"] = [
            {"name": "tk8s-pkg", "mountPath": PACKAGE_MOUNT_PATH, "readOnly": True}
        ]
        pod_spec_extra["volumes"] = [
            {
                "name": "tk8s-pkg",
                "configMap": {"name": PACKAGE_CONFIGMAP_NAME},
            }
        ]
    # Failure recovery (SURVEY.md §5; the reference's node-join converged
    # on re-run, rancherhost/tasks/main.yml:2-9): one lost pod kills the
    # slice's whole JAX cluster — every sibling crashes on the broken
    # collective — so a single recovery costs ~`hosts` pod failures.
    # With a checkpoint dir, budget 3 gang restarts (each retry
    # self-resumes from the latest per-window save); without one a retry
    # would replay the whole run from step 0, so keep fail-fast.
    hosts = config.hosts_per_slice
    return _indexed_tpu_job(
        config,
        name=name,
        job_name=job_name,
        slice_index=slice_index,
        container=container,
        backoff_limit=3 * hosts if checkpoint_dir else 0,
        pod_spec_extra=pod_spec_extra,
    )


# THE host jax pin. The tpuhost role defaults
# (ansible/roles/tpuhost/defaults/main.yml jax_version) must match;
# tests/test_infra.py enforces the equality since YAML can't import this.
JAX_VERSION_PIN = "0.4.38"
PROBE_JAX_PIN = f"jax[tpu]=={JAX_VERSION_PIN}"
PROBE_LIBTPU_INDEX = "https://storage.googleapis.com/jax-releases/libtpu_releases.html"


def to_probe_job(
    config: ClusterConfig,
    *,
    name: str = "tpu-probe",
    image: str = BENCH_IMAGE_DEFAULT,
) -> dict:
    """A short acceptance-test Job: one pod per TPU host — across ALL
    slices — running the JAX device-count smoke test (jax_smoke_command).
    "Chips allocatable" at the node level still doesn't prove a workload
    can enumerate them; this is the deterministic replacement for the
    reference's dashboard-probe workaround (reference setup.sh:59-85) at
    the workload level. Driven by provision/readiness.py run_probe_job.

    Coverage: each pod requests every chip of one host, so with
    completions == total hosts, resource accounting forces exactly one pod
    onto every TPU host — no per-slice pinning needed. The default image
    is a plain python base; the probe self-installs the pinned jax[tpu]
    (same pin as the tpuhost role) so it works without a custom image.
    """
    spec = config.spec
    topo = config.parsed_topology
    total_hosts = config.num_slices * config.hosts_per_slice
    chips_on_host = spec.chips_on_host(topo)
    probe_cmd = (
        f"pip install --quiet '{PROBE_JAX_PIN}' -f {PROBE_LIBTPU_INDEX} && "
        + jax_smoke_command(chips_on_host)
    )
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name, "labels": {"app": name}},
        "spec": {
            "completions": total_hosts,
            "parallelism": total_hosts,
            "completionMode": "Indexed",
            "backoffLimit": 2,
            "ttlSecondsAfterFinished": 600,
            "template": {
                "spec": {
                    "restartPolicy": "Never",
                    "nodeSelector": {
                        "cloud.google.com/gke-tpu-accelerator": _gke_accelerator_label(
                            config.generation
                        ),
                        "cloud.google.com/gke-tpu-topology": str(topo),
                    },
                    "containers": [
                        {
                            "name": "probe",
                            "image": image,
                            "command": ["bash", "-c", probe_cmd],
                            "resources": {
                                "requests": {"google.com/tpu": str(chips_on_host)},
                                "limits": {"google.com/tpu": str(chips_on_host)},
                            },
                        }
                    ],
                }
            },
        },
    }


def to_headless_service(name: str = "resnet50-bench") -> dict:
    """Headless Service for pod-to-pod coordinator discovery (SURVEY.md §7
    'hard parts': coordinator discovery inside K8s)."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": f"{name}-svc"},
        "spec": {
            "clusterIP": "None",  # the k8s API literal, not YAML null
            "selector": {"app": name},
            "ports": [{"port": 8476, "name": "jax-coordinator"}],
        },
    }


def _gke_accelerator_label(generation: str) -> str:
    return {
        "v4": "tpu-v4-podslice",
        "v5e": "tpu-v5-lite-podslice",
        "v5p": "tpu-v5p-slice",
        "v6e": "tpu-v6e-slice",
    }[generation]


def write_manifests(
    config: ClusterConfig,
    manifests_dir: Path,
    workload_image: str = "",
    workload_command: list[str] | None = None,
    workload_name: str = "workload",
    **job_kwargs,
) -> list[Path]:
    """Compile the benchmark Job set — and, when `workload_image` is
    given, a user-supplied (BYO) workload Job set next to it, one Job per
    slice with the same coordinator/topology wiring (the CLI's
    --workload-image/--workload-command; docs/detailed.md §2b)."""
    manifests_dir.mkdir(parents=True, exist_ok=True)
    # the generated dir is owned by this compiler: stale files from a
    # previous (larger) topology must not survive a resize — a leftover
    # bench-job-2.yaml would `kubectl apply` a Job for a slice that no
    # longer exists
    for stale in manifests_dir.glob("*.yaml"):
        stale.unlink()
    paths = []
    # package ConfigMap first: the Job's self-install mount depends on it
    pkg = manifests_dir / "package-configmap.yaml"
    pkg.write_text(yaml.safe_dump(to_package_configmap(), sort_keys=False))
    paths.append(pkg)
    svc = manifests_dir / "bench-service.yaml"
    svc.write_text(yaml.safe_dump(to_headless_service(), sort_keys=False))
    paths.append(svc)
    for i in range(config.num_slices):
        job = manifests_dir / f"bench-job-{i}.yaml"
        job.write_text(
            yaml.safe_dump(to_benchmark_job(config, slice_index=i, **job_kwargs), sort_keys=False)
        )
        paths.append(job)
    if workload_image:
        wsvc = manifests_dir / "workload-service.yaml"
        wsvc.write_text(
            yaml.safe_dump(to_headless_service(workload_name), sort_keys=False)
        )
        paths.append(wsvc)
        for i in range(config.num_slices):
            wjob = manifests_dir / f"workload-job-{i}.yaml"
            wjob.write_text(
                yaml.safe_dump(
                    to_user_workload_job(
                        config,
                        name=workload_name,
                        image=workload_image,
                        command=list(workload_command or []),
                        slice_index=i,
                        cross_slice=job_kwargs.get("cross_slice"),
                    ),
                    sort_keys=False,
                )
            )
            paths.append(wjob)
    return paths
