"""Cluster configuration schema + validation.

The reference collected eight settings through an interactive wizard and
validated them inline (reference setup.sh:255-451): environment
name/description, master hostname (regex ^[a-zA-Z][0-9a-zA-Z]+$ at
setup.sh:276), node prefix, node count (1-9, setup.sh:301), network menu,
package menu. This module is the same contract as data: a typed config
object with pure validation, so the wizard, the file store, and tests all
share one source of truth.
"""

from __future__ import annotations

import dataclasses
import re

from tritonk8ssupervisor_tpu.config import catalog
from tritonk8ssupervisor_tpu.utils.topology import Topology

# GCP resource names: lowercase RFC1035, same spirit as the reference's
# hostname regex (setup.sh:276) but matching what the google provider accepts.
_NAME_RE = re.compile(r"^[a-z][a-z0-9-]{0,61}[a-z0-9]$")

MODES = ("tpu-vm", "gke")

# The reference capped clusters at 9 nodes with a "no HA support" comment
# (setup.sh:297-307). We keep the same guard-rail for slice count.
MAX_SLICES = 9


class ConfigError(ValueError):
    """Invalid cluster configuration; message lists every problem found."""


@dataclasses.dataclass
class ClusterConfig:
    """Everything `setup.sh` needs to stand up (and tear down) a cluster.

    Persisted as flat KEY=value via config/store.py, the analogue of the
    reference `config` file (setup.sh:199-208).
    """

    # Identity / placement (replaces Triton SDC_URL/ACCOUNT, setup.sh:209-239)
    project: str = ""
    zone: str = ""
    # Deployment mode: a standalone TPU VM slice, or a GKE cluster with a
    # TPU node pool (reference had one mode: Triton KVMs joined to Rancher).
    mode: str = "gke"
    # Naming (master hostname / node prefix analogues, setup.sh:274-295)
    cluster_name: str = "tpu-dev"
    node_prefix: str = "tpunode"
    # Environment metadata (kubernetes_name/description, setup.sh:265-271)
    env_name: str = "tpu dev"
    env_description: str = "TPU Kubernetes environment"
    # Accelerator selection (replaces network/package menus, setup.sh:309-450)
    generation: str = catalog.DEFAULT_GENERATION
    topology: str = catalog.DEFAULT_TOPOLOGY
    num_slices: int = 1
    # Networking (reference defaulted to Joyent-SDC-Public, setup.sh:309-400)
    network: str = "default"
    subnetwork: str = "default"
    # Host software (reference pinned docker-engine 1.12.6; we pin the TPU VM
    # runtime image instead — dockersetup/tasks/main.yml:42-46 analogue)
    runtime_version: str = ""  # "" -> generation default from the catalog
    # GKE node identity: default is Workload Identity + minimal node
    # scopes (logging/monitoring/image-pull). True restores the broad
    # cloud-platform node scope — the 2017-era everything-identity the
    # reference's VMs effectively ran with — as an explicit opt-in for
    # clusters that can't use WI bindings yet.
    broad_node_scopes: bool = False
    # Failure domains: how many blast-radius compartments the slices are
    # striped across. 0 (default) = one domain per zone — every slice
    # shares fate (the pre-domain model, exactly). N > 1 stripes slice i
    # into domain `<zone>-fd<i % N>`: machines that share a power feed /
    # ToR / maintenance window share a domain, and the supervisor reacts
    # to a CORRELATED loss (K-of-domain inside a window) with a
    # per-domain circuit breaker + canary re-entry instead of storming
    # heals into the dead compartment (docs/failure-modes.md, "blast
    # radius & correlated failures").
    failure_domains: int = 0

    @property
    def region(self) -> str:
        return self.zone.rsplit("-", 1)[0] if self.zone else ""

    @property
    def spec(self) -> catalog.AcceleratorSpec:
        return catalog.get_spec(self.generation)

    @property
    def parsed_topology(self) -> Topology:
        return self.spec.topology(self.topology)

    @property
    def chips_per_slice(self) -> int:
        return self.parsed_topology.chips

    @property
    def hosts_per_slice(self) -> int:
        return self.spec.hosts(self.parsed_topology)

    @property
    def accelerator_type(self) -> str:
        return catalog.accelerator_type_name(self.generation, self.topology)

    @property
    def effective_runtime_version(self) -> str:
        return self.runtime_version or self.spec.default_runtime

    @property
    def gke_machine_type(self) -> str:
        chips_on_host = self.spec.chips_on_host(self.parsed_topology)
        try:
            return self.spec.gke_machine_type[chips_on_host]
        except KeyError:
            raise ConfigError(
                f"no GKE machine type packs {chips_on_host} {self.generation} "
                f"chips on one host"
            ) from None

    # ---- failure domains ----

    def domain_of(self, slice_index: int) -> str:
        """The failure domain slice `slice_index` belongs to. One domain
        per zone by default; `failure_domains` N stripes slices modulo N
        so every domain holds an equal share of the fleet."""
        n = int(self.failure_domains)
        zone = self.zone or "default"
        if n <= 1:
            return zone
        return f"{zone}-fd{int(slice_index) % n}"

    def domain_map(self) -> dict[int, str]:
        """{slice index: domain name} for the whole fleet."""
        return {i: self.domain_of(i) for i in range(self.num_slices)}

    def domain_slices(self) -> dict[str, list[int]]:
        """{domain name: sorted slice indices} — the classifier's view."""
        grouped: dict[str, list[int]] = {}
        for i in range(self.num_slices):
            grouped.setdefault(self.domain_of(i), []).append(i)
        return grouped

    def validate(self) -> None:
        """Raise ConfigError listing *all* problems (the reference re-prompted
        per field; batch validation serves both wizard and file-loaded configs)."""
        errors: list[str] = []
        if not self.project:
            errors.append("project is required (run `gcloud config set project ...`)")
        if self.mode not in MODES:
            errors.append(f"mode must be one of {MODES}, got {self.mode!r}")
        for field in ("cluster_name", "node_prefix"):
            value = getattr(self, field)
            if not _NAME_RE.match(value):
                errors.append(
                    f"{field} {value!r} must match {_NAME_RE.pattern} "
                    "(lowercase letters, digits, hyphens)"
                )
        if not (1 <= self.num_slices <= MAX_SLICES):
            errors.append(
                f"num_slices must be 1-{MAX_SLICES} (no HA support yet), "
                f"got {self.num_slices}"
            )
        if self.failure_domains < 0:
            errors.append(
                f"failure_domains must be >= 0 (0 = one domain per "
                f"zone), got {self.failure_domains}"
            )
        elif self.failure_domains > self.num_slices:
            errors.append(
                f"failure_domains {self.failure_domains} exceeds "
                f"num_slices {self.num_slices} — a domain with no slices "
                "cannot isolate anything"
            )
        try:
            spec = catalog.get_spec(self.generation)
        except ValueError as e:
            errors.append(str(e))
            spec = None
        if spec is not None:
            try:
                spec.topology(self.topology)
            except ValueError as e:
                errors.append(str(e))
            if self.zone and self.zone not in spec.zones:
                errors.append(
                    f"zone {self.zone!r} has no {self.generation} capacity; "
                    f"known zones: {', '.join(spec.zones)}"
                )
            if not self.zone:
                errors.append(
                    f"zone is required; {self.generation} zones: "
                    f"{', '.join(spec.zones)}"
                )
        if errors:
            raise ConfigError("; ".join(errors))

    # ---- flat KEY=value round-trip (store.py uses these) ----

    _INT_FIELDS = ("num_slices", "failure_domains")
    _BOOL_FIELDS = ("broad_node_scopes",)

    def to_flat(self) -> dict[str, str]:
        return {
            f.name.upper(): str(getattr(self, f.name))
            for f in dataclasses.fields(self)
        }

    @classmethod
    def from_flat(cls, flat: dict[str, str]) -> "ClusterConfig":
        known = {f.name: f for f in dataclasses.fields(cls)}
        kwargs = {}
        for key, value in flat.items():
            name = key.lower()
            if name in known:
                if name in cls._INT_FIELDS:
                    kwargs[name] = int(value)
                elif name in cls._BOOL_FIELDS:
                    kwargs[name] = value.strip().lower() in ("true", "1", "yes")
                else:
                    kwargs[name] = value
        return cls(**kwargs)
