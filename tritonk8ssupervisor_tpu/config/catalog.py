"""Accelerator catalog: which TPU generations exist, which topologies each
supports, how chips pack onto hosts, and where capacity lives.

This is the TPU analogue of the reference's live Triton menus — the
reference pulled `triton networks` / `triton packages` and let the user pick
by ordinal (reference setup.sh:257-259, 309-450, getNetworkIDs at 532-539,
getPackageID at 540-542). TPU offerings are a small static product matrix,
so we ship it as data and validate offline; `gcloud compute tpus
accelerator-types list` can refresh it when credentials exist (see
cli/discovery.py).
"""

from __future__ import annotations

import dataclasses

from tritonk8ssupervisor_tpu.utils.topology import Topology, hosts_for, parse_topology


@dataclasses.dataclass(frozen=True)
class AcceleratorSpec:
    """One TPU generation's provisioning facts."""

    generation: str  # user-facing: "v4" | "v5e" | "v5p" | "v6e"
    type_prefix: str  # Cloud TPU API accelerator-type prefix
    cores_per_chip_in_name: int  # v4/v5p types count TensorCores, v5e/v6e count chips
    topology_ndim: int  # 2 for v5e/v6e, 3 for v4/v5p
    chips_per_host: int  # densest host packing for multi-host slices
    max_chips: int
    topologies: tuple[str, ...]  # valid slice topologies, ascending by chips
    zones: tuple[str, ...]  # zones with capacity (refreshable via gcloud)
    gke_machine_type: dict  # chips-on-host -> GKE machine type
    default_runtime: str  # TPU VM software version

    def topology(self, text: str) -> Topology:
        topo = parse_topology(text)
        if str(topo) not in self.topologies:
            raise ValueError(
                f"topology {topo} is not a valid {self.generation} slice; "
                f"choose one of: {', '.join(self.topologies)}"
            )
        return topo

    def hosts(self, topo: Topology) -> int:
        return hosts_for(topo.chips, self.chips_per_host)

    def chips_on_host(self, topo: Topology) -> int:
        """Chips attached to each host of this slice (uniform for valid slices)."""
        return min(topo.chips, self.chips_per_host)


ACCELERATORS: dict[str, AcceleratorSpec] = {
    "v4": AcceleratorSpec(
        generation="v4",
        type_prefix="v4",
        cores_per_chip_in_name=2,
        topology_ndim=3,
        chips_per_host=4,
        max_chips=4096,
        topologies=(
            "2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8",
            "4x8x8", "8x8x8", "8x8x12", "8x8x16", "8x16x16",
        ),
        zones=("us-central2-b",),
        gke_machine_type={4: "ct4p-hightpu-4t"},
        default_runtime="tpu-ubuntu2204-base",
    ),
    "v5e": AcceleratorSpec(
        generation="v5e",
        type_prefix="v5litepod",
        cores_per_chip_in_name=1,
        topology_ndim=2,
        chips_per_host=8,
        max_chips=256,
        topologies=(
            "1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16",
        ),
        zones=("us-west4-a", "us-east1-c", "us-east5-b", "europe-west4-b"),
        gke_machine_type={1: "ct5lp-hightpu-1t", 4: "ct5lp-hightpu-4t", 8: "ct5lp-hightpu-8t"},
        default_runtime="v2-alpha-tpuv5-lite",
    ),
    "v5p": AcceleratorSpec(
        generation="v5p",
        type_prefix="v5p",
        cores_per_chip_in_name=2,
        topology_ndim=3,
        chips_per_host=4,
        max_chips=8960,
        topologies=(
            "2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8",
            "4x8x8", "8x8x8", "8x8x16", "8x16x16", "16x16x16",
        ),
        zones=("us-east5-a", "us-central1-a", "europe-west4-b"),
        gke_machine_type={4: "ct5p-hightpu-4t"},
        default_runtime="v2-alpha-tpuv5",
    ),
    "v6e": AcceleratorSpec(
        generation="v6e",
        type_prefix="v6e",
        cores_per_chip_in_name=1,
        topology_ndim=2,
        chips_per_host=8,
        max_chips=256,
        topologies=(
            "1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16",
        ),
        zones=("us-east5-b", "us-east1-d", "europe-west4-a", "asia-northeast1-b"),
        gke_machine_type={1: "ct6e-standard-1t", 4: "ct6e-standard-4t", 8: "ct6e-standard-8t"},
        default_runtime="v2-alpha-tpuv6e",
    ),
}

# Wizard default, the analogue of the reference defaulting the package menu
# to k4-highcpu-kvm-7.75G (setup.sh:402-450).
DEFAULT_GENERATION = "v5e"
DEFAULT_TOPOLOGY = "2x2"


def get_spec(generation: str) -> AcceleratorSpec:
    try:
        return ACCELERATORS[generation]
    except KeyError:
        raise ValueError(
            f"unknown TPU generation {generation!r}; "
            f"choose one of: {', '.join(sorted(ACCELERATORS))}"
        ) from None


def accelerator_type_name(generation: str, topology_text: str) -> str:
    """Cloud TPU accelerator-type string, e.g. ("v5e", "4x4") -> "v5litepod-16".

    v4/v5p types count TensorCores (2/chip): ("v4", "2x2x1") -> "v4-8".
    """
    spec = get_spec(generation)
    topo = spec.topology(topology_text)
    return f"{spec.type_prefix}-{topo.chips * spec.cores_per_chip_in_name}"
