"""Flat KEY=value config persistence.

Mirrors the reference's `config` file contract: written by setConfigToFile
(setup.sh:199-208), re-exported into the process environment by exportVars
(setup.sh:543-549), and its *existence* doubles as the "a run is already in
flight" guard (setup.sh:241-244). Keeping the same shape keeps the same
crash-resume property: every phase's inputs live in files the next phase
re-reads.
"""

from __future__ import annotations

import os
from pathlib import Path

from tritonk8ssupervisor_tpu.config.schema import ClusterConfig

CONFIG_FILENAME = "config"


def save_config_file(config: ClusterConfig, path: Path) -> None:
    lines = [f"{k}={v}" for k, v in config.to_flat().items()]
    path.write_text("\n".join(lines) + "\n")


def parse_flat(text: str) -> dict[str, str]:
    """Parse flat KEY=value lines (comments/blank lines skipped). Shared by
    the config file and /etc/tpu-cluster.env (parallel/distributed.py) —
    one definition of the flat-file format."""
    flat: dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        key, _, value = line.partition("=")
        flat[key.strip()] = value.strip()
    return flat


def load_config_file(path: Path) -> ClusterConfig:
    return ClusterConfig.from_flat(parse_flat(path.read_text()))


def export_to_env(config: ClusterConfig, environ: dict | None = None) -> dict:
    """exportVars analogue (setup.sh:543-549): push config into the env so
    child processes (terraform, ansible) see it."""
    environ = os.environ if environ is None else environ
    environ.update(config.to_flat())
    return environ
