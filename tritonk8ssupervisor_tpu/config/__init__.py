from tritonk8ssupervisor_tpu.config.catalog import (  # noqa: F401
    ACCELERATORS,
    AcceleratorSpec,
    accelerator_type_name,
    get_spec,
)
from tritonk8ssupervisor_tpu.config.schema import (  # noqa: F401
    ClusterConfig,
    ConfigError,
)
from tritonk8ssupervisor_tpu.config.store import (  # noqa: F401
    export_to_env,
    load_config_file,
    save_config_file,
)
