# Outputs read by provision/terraform.py collect_outputs (the
# masters.ip/hosts.ip analogue, reference terraform/master/main.tf:29-31).

output "endpoint" {
  description = "GKE control-plane endpoint"
  value       = google_container_cluster.cluster.endpoint
}

output "cluster_name" {
  value = google_container_cluster.cluster.name
}

output "node_pools" {
  description = "TPU node pool names, one per slice"
  value       = [for pool in google_container_node_pool.tpu_pool : pool.name]
}

output "get_credentials_command" {
  description = "The kubeconfig command of record (the dashboard/kubectl URL banner analogue, reference setup.sh:88-89)"
  value       = "gcloud container clusters get-credentials ${google_container_cluster.cluster.name} --zone ${var.zone} --project ${var.project}"
}
