# Variables for the GKE cluster + TPU node-pool module.
#
# Parity map to the reference's master module vars (reference
# terraform/master/vars.tf:1-23): the Rancher master VM becomes a managed
# GKE control plane; the worker "package" menu becomes machine_type +
# tpu_topology. Values arrive via terraform.tfvars.json
# (config/compile.py to_tfvars, gke branch).

variable "project" {
  type        = string
  description = "GCP project to provision into"
}

variable "zone" {
  type        = string
  description = "Zone with TPU capacity"
}

variable "cluster_name" {
  type        = string
  default     = "tpu-dev"
  description = "GKE cluster name (the master hostname analogue, reference setup.sh:274-283)"
}

variable "name_prefix" {
  type        = string
  default     = "tpunode"
  description = "TPU node-pool name prefix (the node-prefix analogue, reference setup.sh:286-295)"
}

variable "num_slices" {
  type        = number
  default     = 1
  description = "TPU node pools (one per slice), 1-9 wizard-capped (reference setup.sh:297-307)"
}

variable "machine_type" {
  type        = string
  description = "TPU machine type packing the slice's chips-per-host, e.g. ct5lp-hightpu-8t"
}

variable "tpu_topology" {
  type        = string
  description = "Physical slice topology, e.g. 4x4 (drives GKE placement)"
}

variable "nodes_per_slice" {
  type        = number
  default     = 1
  description = "TPU VM hosts backing each slice (topology chips / chips-per-host)"
}

variable "network" {
  type        = string
  default     = "default"
  description = "VPC network"
}

variable "subnetwork" {
  type        = string
  default     = "default"
  description = "VPC subnetwork"
}

variable "broad_node_scopes" {
  type        = bool
  default     = false
  description = "Opt out of minimal node scopes: give nodes the broad cloud-platform scope instead of Workload Identity bindings (pre-WI clusters only)"
}
