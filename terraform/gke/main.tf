# GKE cluster with TPU node pools.
#
# The control-plane rebuild of the reference's Rancher stack: instead of a
# master VM running rancher/server + an HTTP API to create a Kubernetes
# environment and join agents (reference ranchermaster/tasks/main.yml:6-49,
# rancherhost/tasks/main.yml:26-34), a managed GKE control plane and TPU
# node pools whose nodes register themselves — the entire L3/L4 node-join
# machinery becomes declarative.
#
# Multi-host slices: a node pool with placement_policy.tpu_topology gives
# the pool's nodes a single physical slice with ICI between chips; GKE
# injects the TPU device plugin (google.com/tpu) and topology metadata that
# the benchmark Job's jax.distributed.initialize consumes
# (config/compile.py to_benchmark_job).

terraform {
  required_providers {
    google = {
      source  = "hashicorp/google"
      version = ">= 5.0"
    }
  }
}

provider "google" {
  project = var.project
  zone    = var.zone
}

resource "google_container_cluster" "cluster" {
  name     = var.cluster_name
  location = var.zone

  # Provider >= 5.0 defaults deletion_protection to true, which makes
  # `./setup.sh -c` (terraform destroy, the cleanRunner analogue,
  # reference setup.sh:498-503) error out on a live cluster. This tool
  # owns the cluster lifecycle end to end, so destroy must work.
  deletion_protection = false

  # The default pool only hosts system pods (the master's "everything else"
  # role in the reference); TPU pools are added per slice below.
  initial_node_count       = 1
  remove_default_node_pool = false

  network    = var.network
  subnetwork = var.subnetwork

  release_channel {
    channel = "REGULAR"
  }

  # Workload Identity: pods authenticate as Kubernetes service accounts
  # federated into IAM, so storage/API access is granted per workload
  # (e.g. the checkpoint bucket binding in docs/benchmarks.md) instead
  # of riding whatever the node can reach.
  workload_identity_config {
    workload_pool = "${var.project}.svc.id.goog"
  }
}

resource "google_container_node_pool" "tpu_pool" {
  count = var.num_slices

  name     = "${var.name_prefix}-${count.index}"
  cluster  = google_container_cluster.cluster.name
  location = var.zone

  # All hosts of one slice, scheduled together on one physical slice.
  node_count = var.nodes_per_slice

  # Node-level elasticity (SURVEY.md §5 failure recovery): GKE replaces
  # failed/unhealthy TPU nodes automatically; the benchmark Job's gang
  # restart budget (config/compile.py backoffLimit) rides on top — the
  # node comes back via auto_repair, the JAX cluster re-forms via the
  # Job retry, training resumes from the latest checkpoint.
  # auto_upgrade stays off: an unsolicited node-pool upgrade mid-run is
  # a self-inflicted preemption.
  management {
    auto_repair  = true
    auto_upgrade = false
  }

  # GKE rejects compact placement / tpu_topology for single-host slice
  # pools — the chips are already co-located on one machine.
  dynamic "placement_policy" {
    for_each = var.nodes_per_slice > 1 ? [1] : []
    content {
      type         = "COMPACT"
      tpu_topology = var.tpu_topology
    }
  }

  node_config {
    machine_type = var.machine_type

    # GKE reserves google.com/tpu on these nodes; workloads request chips
    # the way the reference's docs deployed workloads onto joined nodes
    # (reference docs/detailed.md:255-371).
    labels = {
      role  = "tpu-worker"
      slice = tostring(count.index)
    }

    # Minimal node identity by default: image pulls + logs + metrics.
    # Workload permissions come from Workload Identity bindings, not the
    # node. broad_node_scopes=true restores the old cloud-platform
    # everything-scope for clusters that can't take WI bindings yet.
    oauth_scopes = var.broad_node_scopes ? [
      "https://www.googleapis.com/auth/cloud-platform",
      ] : [
      "https://www.googleapis.com/auth/devstorage.read_only",
      "https://www.googleapis.com/auth/logging.write",
      "https://www.googleapis.com/auth/monitoring",
    ]

    # GKE_METADATA serves each pod its Workload Identity credentials (and
    # blocks the node's own service-account token from workloads).
    workload_metadata_config {
      mode = "GKE_METADATA"
    }
  }
}
