# Variables for the standalone Cloud TPU VM slice module.
#
# Parity map to the reference's host module vars (reference
# terraform/host/vars.tf:1-23): hostname -> name_prefix (+ count fan-out),
# networks -> network/subnetwork, image -> runtime_version,
# package -> accelerator_type, root_authorized_keys -> (GCP project SSH
# metadata; no per-VM key injection needed).
#
# Unlike the reference — which code-generated one module block per VM in
# bash (setup.sh:148-152) — fan-out lives in HCL `count`, driven by
# num_slices from terraform.tfvars.json (config/compile.py).

variable "project" {
  type        = string
  description = "GCP project to provision into"
}

variable "zone" {
  type        = string
  description = "Zone with TPU capacity (validated by the wizard catalog)"
}

variable "name_prefix" {
  type        = string
  default     = "tpunode"
  description = "Slice VM name prefix; slices are <prefix>-0..N-1"
}

variable "num_slices" {
  type        = number
  default     = 1
  description = "Independent TPU slices to provision (1-9, wizard-capped)"
}

variable "accelerator_type" {
  type        = string
  default     = "v5litepod-4"
  description = "Cloud TPU accelerator type, e.g. v5litepod-16 / v4-8"
}

variable "runtime_version" {
  type        = string
  default     = "v2-alpha-tpuv5-lite"
  description = "TPU VM software version (the pinned-docker-engine analogue, reference dockersetup/tasks/main.yml:42-46)"
}

variable "network" {
  type        = string
  default     = "default"
  description = "VPC network (the Joyent-SDC-Public default analogue, reference setup.sh:309-400)"
}

variable "subnetwork" {
  type        = string
  default     = "default"
  description = "VPC subnetwork"
}
