# Declared outputs replace the reference's local-exec appends to
# masters.ip / hosts.ip (reference terraform/master/main.tf:29-31,
# terraform/host/main.tf:29-31). provision/terraform.py persists these to
# terraform/hosts.json — the phase contract the ansible layer requires
# (reference setup.sh:117-120).

output "host_ips" {
  description = "Per-slice list of worker host external IPs (Ansible inventory source)"
  value = [
    for slice in google_tpu_v2_vm.slice : [
      for endpoint in slice.network_endpoints :
      endpoint.access_config[0].external_ip
    ]
  ]
}

output "internal_ips" {
  description = "Per-slice list of worker host internal IPs (coordinator address source)"
  value = [
    for slice in google_tpu_v2_vm.slice : [
      for endpoint in slice.network_endpoints : endpoint.ip_address
    ]
  ]
}

output "slice_names" {
  description = "Cloud TPU resource names, one per slice"
  value       = [for slice in google_tpu_v2_vm.slice : slice.name]
}
