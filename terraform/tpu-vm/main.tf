# Standalone Cloud TPU VM slices.
#
# The TPU-native rebuild of the reference's per-VM Triton module
# (reference terraform/host/main.tf:1-36). What changes and why:
#  - `triton_machine` KVM -> `google_tpu_v2_vm`: one resource is a whole
#    pod slice (possibly many hosts), not a single VM.
#  - the remote-exec bootstrap (sleep 30 + root key copy + python install,
#    reference terraform/master/main.tf:13-27) is gone: TPU runtime images
#    ship python3, and SSH keys come from project metadata.
#  - the local-exec IP-file append (reference terraform/master/main.tf:29-31)
#    is replaced by declared outputs (outputs.tf) read via
#    `terraform output -json` (provision/terraform.py).

terraform {
  required_providers {
    google = {
      source  = "hashicorp/google"
      version = ">= 5.0"
    }
  }
}

provider "google" {
  project = var.project
  zone    = var.zone
}

resource "google_tpu_v2_vm" "slice" {
  count = var.num_slices

  # Names match the readiness prober's expectation (provision/readiness.py
  # polls `gcloud compute tpus tpu-vm describe <name_prefix>-<i>`).
  name             = "${var.name_prefix}-${count.index}"
  zone             = var.zone
  accelerator_type = var.accelerator_type
  runtime_version  = var.runtime_version

  network_config {
    network            = var.network
    subnetwork         = var.subnetwork
    enable_external_ips = true
  }

  # Same operator-facing tags idea as the reference's duplicated tags
  # blocks (terraform/host/main.tf:6-8,33-35), minus the duplication.
  labels = {
    role  = "tpu-worker"
    slice = tostring(count.index)
  }
}
