#!/usr/bin/env python3
"""Driver benchmark entrypoint: ONE JSON line on stdout.

Runs all four benchmark families on whatever accelerator is present —
the real TPU chip under the driver, the virtual CPU mesh in CI:

- ResNet-50 training (BASELINE.json metric: images/sec/chip) — the
  flagship; its metric/value/unit/vs_baseline stay top-level, which is
  the four-field contract the driver reads.
- Transformer-LM training (tokens/sec/chip) — the long-context
  companion; its record rides in the `benchmarks` array of the same
  line so BENCH_r{N}.json regression-guards both families round over
  round (r03 verdict weak #3: half the benchmark surface was invisible
  to the driver).

vs_baseline semantics: ResNet is measured against the up-front target
recorded in BASELINE.md (1000 images/sec/chip for bf16 on a v5e — the
reference repo publishes no accelerator numbers, SURVEY.md §6). The LM
family had no up-front target; its vs_baseline is measured against the
first driver-tracked number (r03: 98,327 tok/s/chip on the same chip),
so it is a round-over-round regression guard rather than a beat-the-
target score.
"""

from __future__ import annotations

import contextlib
import json
import os
import signal
import sys

# images/sec/chip target for ResNet-50 bf16 on TPU v5e (see BASELINE.md)
TPU_BASELINE_IMG_S_CHIP = 1000.0
# tokens/sec/chip for the 12L/768d seq-1024 LM, as first measured on the
# v5e in r03 (docs/benchmarks.md) — the regression-guard baseline
TPU_BASELINE_TOK_S_CHIP = 98327.0
# images/sec/chip for ViT-S/16 bf16 bs256, as first measured on the v5e
# in r04 (docs/benchmarks.md) — round-over-round regression guard
TPU_BASELINE_VIT_IMG_S_CHIP = 2612.0
# decode tokens/sec/chip (GPT-2-small class, prompt 128, 512 new), as
# measured on the v5e in r04 (docs/benchmarks.md): batch 1 with int8
# weights 2084; batch 8 with int8 weights 6775. r05 adds the int8 KV
# cache to the batch-8 config (the regime its roofline says it pays).
TPU_BASELINE_DECODE_B1_TOK_S = 2084.0
TPU_BASELINE_DECODE_B8_TOK_S = 6775.0


def _common_fields(result: dict) -> dict:
    return {
        "platform": result["platform"],
        "num_chips": result["num_chips"],
        "global_batch": result["global_batch"],
        "step_ms": round(result["step_ms"], 2),
        "step_ms_min": round(result["step_ms_min"], 2),
        "step_ms_windows": result["step_ms_windows"],
        "mfu": round(result["mfu"], 4) if result["mfu"] is not None else None,
    }


def resnet_record(on_tpu: bool) -> dict:
    from tritonk8ssupervisor_tpu.benchmarks.resnet50 import run_benchmark

    if on_tpu:
        # 100-step windows: the host-fetch fence that closes a window costs
        # one host<->device round trip (~77 ms through the axon tunnel);
        # over 20-step windows that inflated step time by ~3.9 ms/step in
        # r01/r02. 3 windows give a min/median spread so deltas are
        # attributable (VERDICT r02 weak #7).
        result = run_benchmark(
            model_name="resnet50",
            batch_per_chip=256,
            image_size=224,
            steps=100,
            warmup=5,
            windows=3,
        )
    else:
        # CPU smoke: tiny shapes, same code path end to end
        result = run_benchmark(
            model_name="resnet18",
            batch_per_chip=8,
            image_size=64,
            num_classes=100,
            steps=3,
            warmup=1,
        )
    value = result["images_per_sec_per_chip"]
    return {
        "metric": f"{result['model']}_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / TPU_BASELINE_IMG_S_CHIP, 4),
        **_common_fields(result),
        "flops_per_image": result["flops_per_image"],
    }


def vit_record(on_tpu: bool) -> dict:
    from tritonk8ssupervisor_tpu.benchmarks.resnet50 import run_benchmark

    if on_tpu:
        # ViT-S/16, same harness/discipline as the flagship; 2 windows
        # (spread was 0.02 ms in the r04 measurement) keep the driver
        # pass under a minute after compile
        result = run_benchmark(
            model_name="vit",
            batch_per_chip=256,
            image_size=224,
            steps=100,
            warmup=5,
            windows=2,
        )
    else:
        result = run_benchmark(
            model_name="vit",
            batch_per_chip=8,
            image_size=32,
            num_classes=100,
            steps=3,
            warmup=1,
            windows=1,
        )
    value = result["images_per_sec_per_chip"]
    # CPU smoke runs a different shape entirely — name the series apart
    # so a metric-keyed guard never compares it against the v5e baseline
    # (same contract as the LM's _smoke suffix and resnet18-vs-50)
    name = "vit" if on_tpu else "vit_smoke"
    return {
        "metric": f"{name}_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / TPU_BASELINE_VIT_IMG_S_CHIP, 4),
        **_common_fields(result),
        "flops_per_image": result["flops_per_image"],
    }


def lm_record(on_tpu: bool) -> dict:
    from tritonk8ssupervisor_tpu.benchmarks.lm import run_benchmark

    # The CPU smoke runs a 2L/64d toy, not the 12L/768d configuration the
    # 98,327 tok/s baseline was measured on — name it apart so a guard
    # keyed on metric never compares the two series (the ResNet family
    # disambiguates the same way via its model name). Keep in sync with
    # main()'s lm_name for the failure-stub record.
    name = "transformer_lm" if on_tpu else "transformer_lm_smoke"
    if on_tpu:
        # Same model/seq/batch as the r03 baseline measurement
        # (docs/benchmarks.md); attention rides the benchmark's default
        # ("auto" — the r04-tuned fused kernel on TPU, measured 1.4x
        # dense at this length), so vs_baseline records the real
        # round-over-round throughput of the shipped configuration.
        result = run_benchmark(
            seq_len=1024,
            batch_per_data_shard=8,
            steps=50,
            warmup=3,
            windows=3,
        )
    else:
        # CPU smoke: tiny shapes, dense attention, same code path
        result = run_benchmark(
            vocab_size=256,
            num_layers=2,
            num_heads=2,
            embed_dim=64,
            seq_len=64,
            batch_per_data_shard=1,
            steps=2,
            warmup=1,
            windows=1,
        )
    value = result["tokens_per_sec_per_chip"]
    return {
        "metric": f"{name}_tokens_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(value / TPU_BASELINE_TOK_S_CHIP, 4),
        **_common_fields(result),
        "seq_len": result["seq_len"],
        "attention": result["attention"],
        "flops_per_token": result["flops_per_token"],
    }


def decode_records(on_tpu: bool) -> list[dict]:
    """The serving family (r4 verdict missing #4: decode numbers lived
    only in the docs, self-reported). Two regimes, per the measured
    decode roofline: batch 1 (weight-read bound — int8 weights are the
    lever) and batch 8 (cache-read bound — int8 weights + int8 KV
    cache). vs_baseline anchors to r04's measured v5e numbers, so the
    KV-cache quantization shows up as >1 on the batch-8 row."""
    from tritonk8ssupervisor_tpu.benchmarks.decode import run_benchmark

    if on_tpu:
        # batch 1 runs are short (~0.25 s each) and the tunnel adds
        # ~5% day-to-day jitter — extra repeats tighten the median
        configs = [
            ("decode_b1_int8", TPU_BASELINE_DECODE_B1_TOK_S,
             dict(batch=1, int8=True, repeats=7)),
            ("decode_b8_int8_cache_int8", TPU_BASELINE_DECODE_B8_TOK_S,
             dict(batch=8, int8=True, cache_int8=True)),
        ]
    else:
        # CPU smoke: tiny model, both quantizations through the same path
        # batch must cover the 8-way CPU mesh's data-parallel degree
        configs = [
            ("decode_smoke", 1.0,
             dict(vocab_size=256, num_layers=2, num_heads=2, embed_dim=64,
                  prompt_len=8, new_tokens=8, batch=8, repeats=1,
                  int8=True, cache_int8=True)),
        ]
    records = []
    for name, baseline, kw in configs:
        # per-config isolation: one config's failure (e.g. batch 1 not
        # dividing a multi-chip mesh) must not erase the other's row —
        # same failed-vs-never-ran contract as the family loop in main()
        try:
            result = run_benchmark(**kw)
        except Exception as exc:  # noqa: BLE001 - stub this row only
            print(f"{name} failed ({exc!r}); emitting stub",
                  file=sys.stderr)
            records.append({
                "metric": f"{name}_tokens_per_sec_per_chip",
                "error": repr(exc),
            })
            continue
        value = result["decode_tokens_per_sec_per_chip"]
        records.append({
            "metric": f"{name}_tokens_per_sec_per_chip",
            "value": round(value, 2),
            "unit": "tokens/sec/chip",
            "vs_baseline": round(value / baseline, 4),
            "platform": result["platform"],
            "num_chips": result["num_chips"],
            "batch": result["batch"],
            "prompt_len": result["prompt_len"],
            "new_tokens": result["new_tokens"],
            "int8": result["int8"],
            "cache_int8": result["cache_int8"],
            "ms_per_token_per_stream": round(
                result["ms_per_token_per_stream"], 3),
            "seconds_min": round(result["seconds_min"], 3),
        })
    return records


@contextlib.contextmanager
def family_deadline(seconds: int):
    """Bound one benchmark family's wall time (SIGALRM -> TimeoutError).

    The tunneled chip can wedge (r5 observed a ~40-minute outage where
    even a 64x64 matmul never returned); without a bound the driver
    gets NO json line at all. With it, a hung family raises into the
    per-family stub handling and the line still reports what ran and
    what timed out. Honest limits: a signal only interrupts Python
    bytecode, so a call hard-blocked inside the PJRT C++ runtime won't
    unwind until it yields (polling-loop hangs do; some RPC blocks
    don't), and the alarm spans the whole family — a caught in-family
    timeout leaves later configs of that family unbounded. Override
    via TK8S_BENCH_FAMILY_TIMEOUT; 0 disables (non-main-thread callers
    are skipped automatically)."""
    seconds = int(os.environ.get("TK8S_BENCH_FAMILY_TIMEOUT", seconds))
    import threading

    if seconds <= 0 or threading.current_thread() is not threading.main_thread():
        yield
        return

    def on_alarm(signum, frame):
        raise TimeoutError(f"benchmark family exceeded {seconds}s "
                           "(wedged device/tunnel?)")

    previous = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, previous)


def probe_device(timeout_s: int = 300) -> str | None:
    """Prove the accelerator answers before committing to it: a tiny
    matmul in a SUBPROCESS with a hard timeout. A wedged tunnel blocks
    inside the PJRT C++ runtime where SIGALRM can't unwind (r5: the
    chip went dark for hours mid-round; in-process deadlines never
    fired), but a killed subprocess always comes back. Returns None
    when healthy, else the failure description. Override/disable via
    TK8S_BENCH_PROBE_TIMEOUT (0 skips the probe)."""
    import subprocess

    timeout_s = int(os.environ.get("TK8S_BENCH_PROBE_TIMEOUT", timeout_s))
    if timeout_s <= 0:
        return None
    code = ("import jax, jax.numpy as jnp; "
            "print(float((jnp.ones((64, 64)) @ jnp.ones((64, 64))).sum()))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return f"device probe timed out after {timeout_s}s (wedged tunnel?)"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-1:]
        return f"device probe failed rc={proc.returncode}: {tail}"
    return None


def main() -> int:
    probe_error = probe_device()
    if probe_error is not None:
        # no working device: emit the full all-stub line immediately so
        # the driver records "failed this round" instead of nothing
        print(f"{probe_error}; emitting stub record", file=sys.stderr)
        stub = {
            "metric": "resnet50_images_per_sec_per_chip",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": probe_error,
        }
        print(json.dumps({**stub, "benchmarks": [stub]}, sort_keys=True))
        return 0

    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    try:
        with family_deadline(1200):
            resnet = resnet_record(on_tpu)
    except Exception as exc:  # noqa: BLE001 - emit a parseable stub line
        # even the flagship failing must not leave the driver without a
        # line: all four driver-read fields present, value 0, error set
        resnet = {
            "metric": "resnet50_images_per_sec_per_chip",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": repr(exc),
        }
        print(f"resnet family failed ({exc!r}); emitting stub",
              file=sys.stderr)
    families = [resnet]
    # A companion-family failure must not discard the already-measured
    # flagship record — the driver's four-field contract rides on
    # ResNet. Failed families emit an error stub under the SAME series
    # name the success path would use: a guard must be able to tell
    # "failed this round" from "never ran" (e.g. r01-r03 records).
    lm_name = "transformer_lm" if on_tpu else "transformer_lm_smoke"
    vit_name = "vit" if on_tpu else "vit_smoke"
    companions = [
        (f"{lm_name}_tokens_per_sec_per_chip", lm_record),
        (f"{vit_name}_images_per_sec_per_chip", vit_record),
    ]
    for series, record_fn in companions:
        try:
            with family_deadline(900):
                families.append(record_fn(on_tpu))
        except Exception as exc:  # noqa: BLE001 - report, keep the flagship
            print(f"{series} failed ({exc!r}); emitting stub",
                  file=sys.stderr)
            families.append({"metric": series, "error": repr(exc)})
    decode_series = ("decode_b1_int8_tokens_per_sec_per_chip"
                     if on_tpu else "decode_smoke_tokens_per_sec_per_chip")
    try:
        with family_deadline(900):
            families.extend(decode_records(on_tpu))
    except Exception as exc:  # noqa: BLE001 - report, keep the flagship
        print(f"{decode_series} failed ({exc!r}); emitting stub",
              file=sys.stderr)
        families.append({"metric": decode_series, "error": repr(exc)})
    record = {
        # the four driver-read fields (flagship family)
        **resnet,
        # both families, machine-readable, for round-over-round guarding
        "benchmarks": families,
    }
    print(json.dumps(record, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
