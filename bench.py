#!/usr/bin/env python3
"""Driver benchmark entrypoint: ONE JSON line on stdout.

Runs the flagship ResNet-50 training benchmark (BASELINE.json metric:
images/sec/chip) on whatever accelerator is present — the real TPU chip
under the driver, the virtual CPU mesh in CI.

vs_baseline is measured against the target recorded in BASELINE.md:
1000 images/sec/chip for ResNet-50 bf16 on a v5e chip (the reference
repo publishes no accelerator numbers — SURVEY.md §6 — so the target is
the public ballpark for this chip generation, recorded up front so every
round is comparable).
"""

from __future__ import annotations

import json
import sys

# images/sec/chip target for ResNet-50 bf16 on TPU v5e (see BASELINE.md)
TPU_BASELINE_IMG_S_CHIP = 1000.0


def main() -> int:
    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    from tritonk8ssupervisor_tpu.benchmarks.resnet50 import run_benchmark

    if on_tpu:
        # 100-step windows: the host-fetch fence that closes a window costs
        # one host<->device round trip (~77 ms through the axon tunnel);
        # over 20-step windows that inflated step time by ~3.9 ms/step in
        # r01/r02. 3 windows give a min/median spread so deltas are
        # attributable (VERDICT r02 weak #7).
        result = run_benchmark(
            model_name="resnet50",
            batch_per_chip=256,
            image_size=224,
            steps=100,
            warmup=5,
            windows=3,
        )
    else:
        # CPU smoke: tiny shapes, same code path end to end
        result = run_benchmark(
            model_name="resnet18",
            batch_per_chip=8,
            image_size=64,
            num_classes=100,
            steps=3,
            warmup=1,
        )

    value = result["images_per_sec_per_chip"]
    record = {
        "metric": f"{result['model']}_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / TPU_BASELINE_IMG_S_CHIP, 4),
        # context fields (driver reads the four above; humans read these)
        "platform": result["platform"],
        "num_chips": result["num_chips"],
        "global_batch": result["global_batch"],
        "step_ms": round(result["step_ms"], 2),
        "step_ms_min": round(result["step_ms_min"], 2),
        "step_ms_windows": result["step_ms_windows"],
        "mfu": round(result["mfu"], 4) if result["mfu"] is not None else None,
        "flops_per_image": result["flops_per_image"],
    }
    print(json.dumps(record, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
