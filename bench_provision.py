#!/usr/bin/env python3
"""Provisioning-pipeline benchmark: sequential vs. DAG wall-clock on a
simulated multi-slice cluster. ONE JSON document, no cloud, no sleeps.

The north-star metric is `setup.sh`→ready wall-clock (<15 min,
BASELINE.md), but until real TPU quota exists that number cannot be
measured live — and the pipeline's SHAPE (what overlaps what) can.
This benchmark replays the provision DAG (cli/main.py
build_provision_dag's edges, with readiness fanned out per slice the
way the concurrent probes fan out per host) on a virtual clock
(testing/simclock.py) against a strictly-sequential baseline — the
reference's bash `main` shape — and reports the makespan ratio. The
phase durations are a MODEL (scaled from utils/phases.py
PHASE_BUDGETS, not a measurement); what the benchmark proves is the
schedule: how much of the sequential wall-clock the DAG's overlap
removes, and that the measured win equals the critical-path prediction
exactly. The first real-quota run replaces the model with measured
runlog spans (docs/performance.md).

PR 3 adds the resilience drills (`--resilience`): the same simulated
4-slice provision is SIGKILL'd mid-DAG (testing/faults.py `kill` rule)
and resumed from the durable journal (provision/journal.py), reporting
MTTR and the redo-work ratio (resume must redo < 30% of a cold run);
then a single slice is lost and repaired via `heal` (provision/heal.py),
asserting the scoped terraform replace addressed ONLY the lost slice and
healthy slices' tfstate entries are byte-identical afterwards.

Usage::

    python bench_provision.py [--slices 4] [--out BENCH_provision.json]
    python bench_provision.py --resilience [--out BENCH_resilience.json]
"""

from __future__ import annotations

import argparse
import io
import json
import shutil
import sys
import tempfile
from pathlib import Path

from tritonk8ssupervisor_tpu.provision import journal as journal_mod
from tritonk8ssupervisor_tpu.provision.scheduler import (
    Task,
    critical_path,
    run_dag,
    validate,
)
from tritonk8ssupervisor_tpu.testing.simclock import SimClock
from tritonk8ssupervisor_tpu.utils.phases import PhaseTimer

# Simulated phase durations (seconds) for ONE provision of a tpu-vm
# cluster — the per-phase budgets of utils/phases.py with readiness
# split into its per-slice constituents (TPU state poll, then the
# authenticated-SSH gate), which is where the concurrency lives:
# terraform's count fan-out creates slices in parallel, so their
# readiness clocks tick together, but the sequential pipeline PROBED
# them one after another and paid the sum.
SIM_SECONDS = {
    "terraform-apply": 300.0,
    "compile-manifests": 20.0,
    "tpu-state-slice": 75.0,  # per slice: QueuedResource -> READY poll
    "ssh-ready-slice": 45.0,  # per slice: sshd accepting auth sessions
    "host-configuration": 150.0,
}


def build_sim_tasks(
    clock: SimClock, num_slices: int
) -> tuple[list[Task], dict[str, float]]:
    """The provision DAG with per-slice readiness tasks. Returns the
    tasks plus {name: simulated seconds} for the critical-path check."""

    durations: dict[str, float] = {}

    def sim(name: str, seconds: float):
        durations[name] = seconds

        def fn(results: dict) -> float:
            clock.begin()
            clock.sleep(seconds)
            return seconds

        return fn

    tasks = [
        Task("terraform-apply",
             sim("terraform-apply", SIM_SECONDS["terraform-apply"])),
        Task("compile-manifests",
             sim("compile-manifests", SIM_SECONDS["compile-manifests"])),
    ]
    ssh_names = []
    for i in range(num_slices):
        tpu = f"tpu-state-slice-{i}"
        ssh = f"ssh-ready-slice-{i}"
        tasks.append(
            Task(tpu, sim(tpu, SIM_SECONDS["tpu-state-slice"]),
                 after=("terraform-apply",))
        )
        tasks.append(Task(ssh, sim(ssh, SIM_SECONDS["ssh-ready-slice"]),
                          after=(tpu,)))
        ssh_names.append(ssh)
    tasks.append(
        Task("host-configuration",
             sim("host-configuration", SIM_SECONDS["host-configuration"]),
             after=tuple(ssh_names))
    )
    return tasks, durations


def linearize(tasks: list[Task]) -> list[Task]:
    """The sequential baseline: the same tasks chained end to end in
    topological order — exactly the reference's bash `main` shape, where
    nothing starts until everything before it finished."""
    chained: list[Task] = []
    prev: str | None = None
    for task in validate(tasks):
        chained.append(
            Task(task.name, task.fn,
                 after=(prev,) if prev is not None else ())
        )
        prev = task.name
    return chained


def simulate(tasks: list[Task], clock: SimClock, max_workers: int) -> dict:
    """Run the graph on the virtual clock; return makespan + work sum."""
    timer = PhaseTimer(out=io.StringIO(), clock=clock.time, wall=clock.time)
    run_dag(
        tasks,
        max_workers=max_workers,
        timer=timer,
        on_submit=clock.launch,
        on_settled=clock.release,
    )
    return {"wall_s": timer.wall, "work_s": timer.total,
            "phases": dict(timer.durations)}


def run_benchmark(num_slices: int = 4) -> dict:
    """Sequential vs. DAG provision of `num_slices` slices, plus the
    critical-path prediction the DAG makespan must equal."""
    # pool must cover the widest antichain: all slices' probes + the
    # manifest compile riding along terraform
    width = 2 * num_slices + 2

    seq_clock = SimClock()
    seq_tasks, _ = build_sim_tasks(seq_clock, num_slices)
    sequential = simulate(linearize(seq_tasks), seq_clock, max_workers=2)

    dag_clock = SimClock()
    dag_tasks, durations = build_sim_tasks(dag_clock, num_slices)
    dag = simulate(dag_tasks, dag_clock, max_workers=width)

    crit = critical_path(dag_tasks, durations)
    crit_seconds = sum(durations[name] for name in crit)
    return {
        "benchmark": "provision_sim",
        "metric": "provision_wall_clock_speedup",
        "unit": "x (sequential/dag makespan, simulated)",
        "num_slices": num_slices,
        "model_seconds": dict(SIM_SECONDS),
        "sequential": sequential,
        "dag": dag,
        "critical_path": crit,
        "critical_path_s": crit_seconds,
        "value": round(sequential["wall_s"] / dag["wall_s"], 3),
        "dag_matches_critical_path": abs(dag["wall_s"] - crit_seconds) < 1e-6,
    }


# ------------------------------------------------------- resilience drills


def build_journaled_tasks(
    clock: SimClock,
    num_slices: int,
    workdir: Path,
    executed: list,
    plan=None,
) -> tuple[list[Task], dict[str, float]]:
    """The provision DAG shape with journal metadata: each task sleeps
    its modeled duration on the virtual clock, then writes an artifact
    file — so a resume has real inputs-hashes and on-disk digests to
    verify, exactly like the live pipeline's tfstate/hosts.json. `plan`
    is a FaultPlan consulted at task START (kill-at-task fires before
    any virtual time elapses — the task dies with only its fsync'd
    `running` record, the SIGKILL signature)."""
    durations: dict[str, float] = {}
    art_dir = workdir / "artifacts"

    def sim(name: str, seconds: float, after: tuple = ()) -> Task:
        durations[name] = seconds
        artifact = art_dir / f"{name}.out"

        def fn(results: dict) -> float:
            clock.begin()
            if plan is not None:
                plan.fire(name)
            clock.sleep(seconds)
            executed.append(name)
            art_dir.mkdir(parents=True, exist_ok=True)
            artifact.write_text(f"{name}: {seconds}\n")
            return seconds

        return Task(
            name, fn, after=after,
            inputs_hash=journal_mod.inputs_hash(name, seconds),
            artifacts=(artifact,),
            restore=lambda results: durations[name],
        )

    tasks = [
        sim("terraform-apply", SIM_SECONDS["terraform-apply"]),
        sim("compile-manifests", SIM_SECONDS["compile-manifests"]),
    ]
    ssh_names = []
    for i in range(num_slices):
        tpu, ssh = f"tpu-state-slice-{i}", f"ssh-ready-slice-{i}"
        tasks.append(sim(tpu, SIM_SECONDS["tpu-state-slice"],
                         after=("terraform-apply",)))
        tasks.append(sim(ssh, SIM_SECONDS["ssh-ready-slice"], after=(tpu,)))
        ssh_names.append(ssh)
    tasks.append(sim("host-configuration",
                     SIM_SECONDS["host-configuration"],
                     after=tuple(ssh_names)))
    return tasks, durations


def _journaled_run(num_slices: int, workdir: Path, plan=None) -> dict:
    """One DAG execution against the journal at `workdir`: returns the
    executed task list, wall-clock makespan, and the raised kill (if
    any) — the shared leg of the crash-resume drill."""
    from tritonk8ssupervisor_tpu.testing.faults import SupervisorKilled

    clock = SimClock()
    executed: list = []
    tasks, durations = build_journaled_tasks(
        clock, num_slices, workdir, executed, plan=plan
    )
    timer = PhaseTimer(out=io.StringIO(), clock=clock.time, wall=clock.time)
    journal = journal_mod.Journal(
        workdir / "journal.jsonl", echo=lambda line: None
    )
    killed = False
    with journal:
        try:
            run_dag(
                tasks,
                max_workers=2 * num_slices + 2,
                timer=timer,
                journal=journal,
                on_submit=clock.launch,
                on_settled=clock.release,
                echo=lambda line: None,
            )
        except SupervisorKilled:
            killed = True
    return {"executed": executed, "wall_s": timer.wall,
            "durations": durations, "killed": killed}


def run_crash_resume_drill(
    num_slices: int = 4,
    kill_at: str = "ssh-ready-slice-1",
    workdir: Path | None = None,
) -> dict:
    """SIGKILL the supervisor mid-DAG, resume from the journal, and
    measure the redo: the resume must execute strictly fewer tasks than
    a cold run and redo < 30% of the cold run's task-seconds."""
    from tritonk8ssupervisor_tpu.testing.faults import FaultPlan, FaultRule

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-crash-drill-")
    )
    try:
        cold = _journaled_run(num_slices, root / "cold")
        cold_work = sum(cold["durations"][t] for t in cold["executed"])

        crash_dir = root / "crash"
        plan = FaultPlan(
            [FaultRule(match=f"^{kill_at}$", kill=True)],
            echo=lambda line: None,
        )
        crashed = _journaled_run(num_slices, crash_dir, plan=plan)
        assert crashed["killed"], "kill-at-task fault did not fire"

        resumed = _journaled_run(num_slices, crash_dir)
        redo_work = sum(resumed["durations"][t] for t in resumed["executed"])
        return {
            "kill_at": kill_at,
            "cold_tasks": len(cold["executed"]),
            "cold_work_s": cold_work,
            "cold_wall_s": cold["wall_s"],
            "tasks_done_before_kill": len(crashed["executed"]),
            "resumed_tasks": len(resumed["executed"]),
            "resumed_task_names": sorted(resumed["executed"]),
            "redo_work_s": redo_work,
            "mttr_wall_s": resumed["wall_s"],
            "redo_ratio": round(redo_work / cold_work, 4),
            "resume_beats_cold": resumed["wall_s"] < cold["wall_s"],
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


class _Say:
    """Minimal prompter for the drills: collect say() lines."""

    def __init__(self):
        self.lines: list = []

    def say(self, text: str = "") -> None:
        self.lines.append(text)


def run_slice_loss_drill(
    num_slices: int = 4,
    lost_slice: int = 2,
    workdir: Path | None = None,
) -> dict:
    """Lose one slice, repair it through the REAL heal path
    (provision/heal.py -> terraform -replace -> ansible --limit ->
    scoped readiness) against scripted runners, and verify the healthy
    slices' tfstate entries come out byte-identical."""
    from tritonk8ssupervisor_tpu.config.schema import ClusterConfig
    from tritonk8ssupervisor_tpu.provision import heal as heal_mod
    from tritonk8ssupervisor_tpu.provision.state import ClusterHosts, RunPaths

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-heal-drill-")
    )
    try:
        paths = RunPaths(root)
        paths.terraform_module("tpu-vm").mkdir(parents=True, exist_ok=True)
        config = ClusterConfig(
            project="sim-proj", zone="us-west4-a", generation="v5e",
            topology="4x4", mode="tpu-vm", num_slices=num_slices,
        )
        host_ips = [[f"10.0.{i}.1"] for i in range(num_slices)]
        internal = [[f"10.1.{i}.1"] for i in range(num_slices)]
        # one tfstate, one entry per slice — what -replace must scope over
        tfstate = {"resources": [
            {"type": "google_tpu_v2_vm", "name": "slice", "index": i,
             "ip": host_ips[i][0], "generation": 0}
            for i in range(num_slices)
        ]}
        paths.tfstate("tpu-vm").write_text(json.dumps(tfstate, indent=2))
        hosts = ClusterHosts(host_ips=[list(s) for s in host_ips],
                             internal_ips=[list(s) for s in internal],
                             coordinator_ip=internal[0][0])
        # the loss: slice's hosts vanish from the record (maintenance ate
        # the node / terraform state drifted)
        hosts.host_ips[lost_slice] = []
        hosts.internal_ips[lost_slice] = []
        hosts.save(paths.hosts_file)

        healthy_before = {
            r["index"]: json.dumps(r, sort_keys=True)
            for r in tfstate["resources"] if r["index"] != lost_slice
        }
        new_ip = f"10.9.{lost_slice}.1"
        calls: list = []

        def run(args, cwd=None, **kwargs):
            line = " ".join(str(a) for a in args)
            calls.append(line)
            if args[:2] == ["terraform", "apply"]:
                st = json.loads(paths.tfstate("tpu-vm").read_text())
                for a in args:
                    if str(a).startswith("-replace="):
                        idx = int(str(a).split("[")[1].rstrip("]"))
                        for r in st["resources"]:
                            if r["index"] == idx:
                                r["ip"] = new_ip
                                r["generation"] += 1
                paths.tfstate("tpu-vm").write_text(json.dumps(st, indent=2))
            return ""

        def run_quiet(args, cwd=None, **kwargs):
            line = " ".join(str(a) for a in args)
            calls.append(line)
            if args[:3] == ["terraform", "output", "-json"]:
                st = json.loads(paths.tfstate("tpu-vm").read_text())
                by_index = {r["index"]: r for r in st["resources"]}
                return json.dumps({
                    "host_ips": {"value": [
                        [by_index[i]["ip"]] for i in range(num_slices)
                    ]},
                    "internal_ips": {"value": [list(s) for s in internal]},
                })
            if args and args[0] == "gcloud":
                return "\n".join(
                    f"{config.node_prefix}-{i}\tREADY"
                    for i in range(num_slices)
                )
            return ""  # ssh probes / drain checks: reachable, no drain

        prompter = _Say()
        heal_mod.heal(
            config, paths, prompter, run=run, run_quiet=run_quiet,
            readiness_timeout=30.0, sleep=lambda s: None,
        )

        st_after = json.loads(paths.tfstate("tpu-vm").read_text())
        healthy_after = {
            r["index"]: json.dumps(r, sort_keys=True)
            for r in st_after["resources"] if r["index"] != lost_slice
        }
        lost_after = next(r for r in st_after["resources"]
                          if r["index"] == lost_slice)
        hosts_after = ClusterHosts.load(paths.hosts_file)
        replace_args = sorted(
            a for line in calls if line.startswith("terraform apply")
            for a in line.split() if a.startswith("-replace=")
        )
        limit_used = any("--limit" in line and new_ip in line
                         for line in calls if "ansible" in line)
        # modeled MTTR: the heal redoes one slice's provision chain while
        # a cold redeploy pays the full DAG critical path
        heal_model_s = (SIM_SECONDS["tpu-state-slice"]
                        + SIM_SECONDS["ssh-ready-slice"]
                        + SIM_SECONDS["host-configuration"])
        cold_model_s = (SIM_SECONDS["terraform-apply"]
                        + SIM_SECONDS["tpu-state-slice"]
                        + SIM_SECONDS["ssh-ready-slice"]
                        + SIM_SECONDS["host-configuration"])
        return {
            "lost_slice": lost_slice,
            "replace_args": replace_args,
            "scoped_to_lost_slice_only": replace_args == [
                f"-replace=google_tpu_v2_vm.slice[{lost_slice}]"
            ],
            "healthy_tfstate_untouched": healthy_before == healthy_after,
            "lost_slice_recreated": lost_after["generation"] == 1
            and lost_after["ip"] == new_ip,
            "hosts_rewritten": hosts_after.host_ips[lost_slice] == [new_ip],
            "ansible_limited_to_healed_hosts": limit_used,
            "heal_model_s": heal_model_s,
            "cold_redeploy_model_s": cold_model_s,
            "mttr_ratio": round(heal_model_s / cold_model_s, 4),
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_resilience_benchmark(num_slices: int = 4) -> dict:
    """The PR-3 acceptance datapoint: crash-resume + slice-loss drills,
    one BENCH-style JSON document."""
    crash = run_crash_resume_drill(num_slices)
    loss = run_slice_loss_drill(num_slices)
    return {
        "benchmark": "provision_resilience",
        "metric": "crash_resume_redo_ratio",
        "unit": "fraction of cold-run task seconds redone after a "
                "mid-DAG SIGKILL (target < 0.30)",
        "num_slices": num_slices,
        "model_seconds": dict(SIM_SECONDS),
        "value": crash["redo_ratio"],
        "crash_resume": crash,
        "slice_loss": loss,
        "passes": bool(
            crash["redo_ratio"] < 0.30
            and crash["resumed_tasks"] < crash["cold_tasks"]
            and loss["scoped_to_lost_slice_only"]
            and loss["healthy_tfstate_untouched"]
        ),
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--slices", type=int, default=4)
    parser.add_argument("--resilience", action="store_true",
                        help="run the crash-resume + slice-loss drills "
                        "instead of the sequential-vs-DAG comparison")
    parser.add_argument("--out", default=None, metavar="FILE",
                        help="also write the JSON document to FILE")
    args = parser.parse_args(argv)
    if args.resilience:
        result = run_resilience_benchmark(args.slices)
    else:
        result = run_benchmark(args.slices)
    doc = json.dumps(result, indent=2, sort_keys=True)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    if args.resilience:
        crash = result["crash_resume"]
        print(
            f"\n{args.slices}-slice resilience (simulated): SIGKILL at "
            f"{crash['kill_at']} -> resume redid "
            f"{crash['resumed_tasks']}/{crash['cold_tasks']} tasks "
            f"({crash['redo_ratio']:.1%} of cold work, MTTR "
            f"{crash['mttr_wall_s']:.0f}s); slice-loss heal scoped="
            f"{result['slice_loss']['scoped_to_lost_slice_only']} "
            f"healthy-untouched="
            f"{result['slice_loss']['healthy_tfstate_untouched']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    print(
        f"\n{args.slices}-slice provision (simulated): "
        f"sequential {result['sequential']['wall_s']:.0f}s -> "
        f"DAG {result['dag']['wall_s']:.0f}s "
        f"({result['value']:.2f}x; critical path "
        f"{' -> '.join(result['critical_path'])})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
