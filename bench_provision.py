#!/usr/bin/env python3
"""Provisioning-pipeline benchmark: sequential vs. DAG wall-clock on a
simulated multi-slice cluster. ONE JSON document, no cloud, no sleeps.

The north-star metric is `setup.sh`→ready wall-clock (<15 min,
BASELINE.md), but until real TPU quota exists that number cannot be
measured live — and the pipeline's SHAPE (what overlaps what) can.
This benchmark replays the provision DAG (cli/main.py
build_provision_dag's edges, with readiness fanned out per slice the
way the concurrent probes fan out per host) on a virtual clock
(testing/simclock.py) against a strictly-sequential baseline — the
reference's bash `main` shape — and reports the makespan ratio. The
phase durations are a MODEL (scaled from utils/phases.py
PHASE_BUDGETS, not a measurement); what the benchmark proves is the
schedule: how much of the sequential wall-clock the DAG's overlap
removes, and that the measured win equals the critical-path prediction
exactly. The first real-quota run replaces the model with measured
runlog spans (docs/performance.md).

Usage::

    python bench_provision.py [--slices 4] [--out BENCH_provision.json]
"""

from __future__ import annotations

import argparse
import io
import json
import sys

from tritonk8ssupervisor_tpu.provision.scheduler import (
    Task,
    critical_path,
    run_dag,
    validate,
)
from tritonk8ssupervisor_tpu.testing.simclock import SimClock
from tritonk8ssupervisor_tpu.utils.phases import PhaseTimer

# Simulated phase durations (seconds) for ONE provision of a tpu-vm
# cluster — the per-phase budgets of utils/phases.py with readiness
# split into its per-slice constituents (TPU state poll, then the
# authenticated-SSH gate), which is where the concurrency lives:
# terraform's count fan-out creates slices in parallel, so their
# readiness clocks tick together, but the sequential pipeline PROBED
# them one after another and paid the sum.
SIM_SECONDS = {
    "terraform-apply": 300.0,
    "compile-manifests": 20.0,
    "tpu-state-slice": 75.0,  # per slice: QueuedResource -> READY poll
    "ssh-ready-slice": 45.0,  # per slice: sshd accepting auth sessions
    "host-configuration": 150.0,
}


def build_sim_tasks(
    clock: SimClock, num_slices: int
) -> tuple[list[Task], dict[str, float]]:
    """The provision DAG with per-slice readiness tasks. Returns the
    tasks plus {name: simulated seconds} for the critical-path check."""

    durations: dict[str, float] = {}

    def sim(name: str, seconds: float):
        durations[name] = seconds

        def fn(results: dict) -> float:
            clock.begin()
            clock.sleep(seconds)
            return seconds

        return fn

    tasks = [
        Task("terraform-apply",
             sim("terraform-apply", SIM_SECONDS["terraform-apply"])),
        Task("compile-manifests",
             sim("compile-manifests", SIM_SECONDS["compile-manifests"])),
    ]
    ssh_names = []
    for i in range(num_slices):
        tpu = f"tpu-state-slice-{i}"
        ssh = f"ssh-ready-slice-{i}"
        tasks.append(
            Task(tpu, sim(tpu, SIM_SECONDS["tpu-state-slice"]),
                 after=("terraform-apply",))
        )
        tasks.append(Task(ssh, sim(ssh, SIM_SECONDS["ssh-ready-slice"]),
                          after=(tpu,)))
        ssh_names.append(ssh)
    tasks.append(
        Task("host-configuration",
             sim("host-configuration", SIM_SECONDS["host-configuration"]),
             after=tuple(ssh_names))
    )
    return tasks, durations


def linearize(tasks: list[Task]) -> list[Task]:
    """The sequential baseline: the same tasks chained end to end in
    topological order — exactly the reference's bash `main` shape, where
    nothing starts until everything before it finished."""
    chained: list[Task] = []
    prev: str | None = None
    for task in validate(tasks):
        chained.append(
            Task(task.name, task.fn,
                 after=(prev,) if prev is not None else ())
        )
        prev = task.name
    return chained


def simulate(tasks: list[Task], clock: SimClock, max_workers: int) -> dict:
    """Run the graph on the virtual clock; return makespan + work sum."""
    timer = PhaseTimer(out=io.StringIO(), clock=clock.time, wall=clock.time)
    run_dag(
        tasks,
        max_workers=max_workers,
        timer=timer,
        on_submit=clock.launch,
        on_settled=clock.release,
    )
    return {"wall_s": timer.wall, "work_s": timer.total,
            "phases": dict(timer.durations)}


def run_benchmark(num_slices: int = 4) -> dict:
    """Sequential vs. DAG provision of `num_slices` slices, plus the
    critical-path prediction the DAG makespan must equal."""
    # pool must cover the widest antichain: all slices' probes + the
    # manifest compile riding along terraform
    width = 2 * num_slices + 2

    seq_clock = SimClock()
    seq_tasks, _ = build_sim_tasks(seq_clock, num_slices)
    sequential = simulate(linearize(seq_tasks), seq_clock, max_workers=2)

    dag_clock = SimClock()
    dag_tasks, durations = build_sim_tasks(dag_clock, num_slices)
    dag = simulate(dag_tasks, dag_clock, max_workers=width)

    crit = critical_path(dag_tasks, durations)
    crit_seconds = sum(durations[name] for name in crit)
    return {
        "benchmark": "provision_sim",
        "metric": "provision_wall_clock_speedup",
        "unit": "x (sequential/dag makespan, simulated)",
        "num_slices": num_slices,
        "model_seconds": dict(SIM_SECONDS),
        "sequential": sequential,
        "dag": dag,
        "critical_path": crit,
        "critical_path_s": crit_seconds,
        "value": round(sequential["wall_s"] / dag["wall_s"], 3),
        "dag_matches_critical_path": abs(dag["wall_s"] - crit_seconds) < 1e-6,
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--slices", type=int, default=4)
    parser.add_argument("--out", default=None, metavar="FILE",
                        help="also write the JSON document to FILE")
    args = parser.parse_args(argv)
    result = run_benchmark(args.slices)
    doc = json.dumps(result, indent=2, sort_keys=True)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    print(
        f"\n{args.slices}-slice provision (simulated): "
        f"sequential {result['sequential']['wall_s']:.0f}s -> "
        f"DAG {result['dag']['wall_s']:.0f}s "
        f"({result['value']:.2f}x; critical path "
        f"{' -> '.join(result['critical_path'])})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
