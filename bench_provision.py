#!/usr/bin/env python3
"""Provisioning-pipeline benchmark: sequential vs. DAG vs. per-slice
pipelined wall-clock on a simulated multi-slice cluster, plus the warm
re-run. ONE JSON document, no cloud, no sleeps.

The north-star metric is `setup.sh`→ready wall-clock (<15 min,
BASELINE.md), but until real TPU quota exists that number cannot be
measured live — and the pipeline's SHAPE (what overlaps what, what can
be skipped) can. This benchmark replays three schedules on a virtual
clock (testing/simclock.py):

- **sequential** — the reference's bash `main`: everything chained;
- **barrier DAG** — the PR-2 shape: probes fan out per slice, but one
  monolithic `host-configuration` waits for EVERY slice's ssh;
- **pipelined DAG** — the current cli/main.py shape: a short shared
  `host-prep`, then per-slice `converge-slice-N` whose only
  dependencies are host-prep and THAT slice's ssh-ready. The 150 s
  barrier becomes a 55 s per-slice converge (one slice's hosts at full
  fork parallelism and uncontended egress for the ~1 GB jax[tpu] pull,
  instead of the whole fleet contending) that starts the moment its
  slice is up.

The **warm** scenario re-runs the journaled pipelined DAG over an
already-green journal + warm cache: every task verifies and skips, and
the modeled cost is the per-task digest check (`verify-task`), charged
to the same virtual clock. The phase durations are a MODEL (scaled from
utils/phases.py PHASE_BUDGETS, not a measurement); what the benchmark
proves is the schedule and the skip logic. The first real-quota run
replaces the model with measured runlog spans (docs/performance.md).

PR 3's resilience drills (`--resilience`) ride the same harness: a
mid-DAG SIGKILL resumed from the durable journal (MTTR + redo ratio),
and a single-slice loss repaired via `heal` with the warm cache leaving
healthy slices' converge untouched.

PR 5's supervisor drills (`--supervise`) measure UNATTENDED repair: a
slice preempted at t=300 s with the resident reconcile loop running
(provision/supervisor.py) is detected, flap-confirmed, and healed with
zero human input; the recorded MTTR is judged against the PR-4
manual-heal baseline (120 s, an operator already at the keyboard) plus
one reconcile interval. A second drill proves the safety rails: heals
that never stick are spaced by the token bucket, trip the breaker, and
end in degraded-hold — never a replace-loop.

`--check` is the perf-regression gate: re-simulate and fail (exit 1) if
the cold or warm makespan — or the unattended MTTR — regressed more
than 10% against the committed BENCH_provision.json /
BENCH_supervise.json — wired as a tier-1 `perf` test.

Usage::

    python bench_provision.py [--slices 4] [--out BENCH_provision.json]
    python bench_provision.py --warm
    python bench_provision.py --resilience [--out BENCH_resilience.json]
    python bench_provision.py --supervise [--out BENCH_supervise.json]
    python bench_provision.py --chaos [--campaigns 25] [--out BENCH_chaos.json]
    python bench_provision.py --serve [--out BENCH_serve.json]
    python bench_provision.py --autoscale [--campaigns 25] [--out BENCH_autoscale.json]
    python bench_provision.py --allocator [--campaigns 25] [--out BENCH_allocator.json]
    python bench_provision.py --fleet [--campaigns 25] [--out BENCH_fleet.json]
    python bench_provision.py --obs [--out BENCH_obs.json]
    python bench_provision.py --check [--baseline BENCH_provision.json]

The serving drills (`--serve`) put the continuous-batching gateway
(serving/gateway.py) under a SimClock open-loop arrival model — a
diurnal rate curve with burst storms, request-at-a-time vs continuous
batching over the SAME stream, a mid-run slice outage it must route
around, and a breaker-open hold it must shed — reporting p50/p99
latency, queue depth, tokens/sec/chip, and goodput during the outage
(BENCH_serve.json, gated by --check like every other drill).
"""

from __future__ import annotations

import argparse
import io
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

from tritonk8ssupervisor_tpu.provision import journal as journal_mod
from tritonk8ssupervisor_tpu.provision.scheduler import (
    Task,
    critical_path,
    run_dag,
    validate,
)
from tritonk8ssupervisor_tpu.testing.simclock import SimClock
from tritonk8ssupervisor_tpu.utils.phases import PhaseTimer

# Simulated phase durations (seconds) for ONE provision of a tpu-vm
# cluster — the per-phase budgets of utils/phases.py with readiness and
# host configuration split into their per-slice constituents, which is
# where the concurrency lives: terraform's count fan-out creates slices
# in parallel, so their readiness clocks tick together, and a single
# slice's ansible converge needs neither the other slices' sshds nor a
# share of their pip-install bandwidth.
SIM_SECONDS = {
    "terraform-apply": 300.0,
    "compile-manifests": 20.0,
    "tpu-state-slice": 75.0,  # per slice: QueuedResource -> READY poll
    "ssh-ready-slice": 45.0,  # per slice: sshd accepting auth sessions
    "host-prep": 15.0,  # shared: inventory/vars/key patch (local writes)
    "converge-slice": 55.0,  # per slice: ansible --limit, full forks
    "host-configuration": 150.0,  # the pre-split whole-fleet monolith
    "verify-task": 2.0,  # warm path: digest re-check of one task
    # One slice's end-to-end scoped heal (replace -> ready -> converge,
    # overlapped the way the live path overlaps boot and converge): the
    # PR-4 measured manual-heal MTTR (BENCH_resilience.json
    # crash_resume.mttr_wall_s) — the baseline the supervisor's
    # unattended MTTR is judged against.
    "heal-slice": 120.0,
    # Fleet-scale tick cost model (--fleetscale): one windowed `tpu-vm
    # list` page (~1 s of gcloud startup + API latency) and one SSH
    # probe/drain check round-trip. The supervisor's per-tick cost is
    # ops x these, which is what the dirty-set reconcile bounds.
    "fleet-list-page": 1.0,
    "ssh-probe": 0.2,
}


def build_sim_tasks(
    clock: SimClock, num_slices: int, pipelined: bool = True
) -> tuple[list[Task], dict[str, float]]:
    """The provision DAG with per-slice readiness tasks. `pipelined`
    selects the current per-slice converge shape; False reproduces the
    PR-2 barrier (one host-configuration after every slice's ssh).
    Returns the tasks plus {name: simulated seconds} for the
    critical-path check."""

    durations: dict[str, float] = {}

    def sim(name: str, seconds: float):
        durations[name] = seconds

        def fn(results: dict) -> float:
            clock.begin()
            clock.sleep(seconds)
            return seconds

        return fn

    tasks = [
        Task("terraform-apply",
             sim("terraform-apply", SIM_SECONDS["terraform-apply"])),
        Task("compile-manifests",
             sim("compile-manifests", SIM_SECONDS["compile-manifests"])),
    ]
    ssh_names = []
    for i in range(num_slices):
        tpu = f"tpu-state-slice-{i}"
        ssh = f"ssh-ready-slice-{i}"
        tasks.append(
            Task(tpu, sim(tpu, SIM_SECONDS["tpu-state-slice"]),
                 after=("terraform-apply",))
        )
        tasks.append(Task(ssh, sim(ssh, SIM_SECONDS["ssh-ready-slice"]),
                          after=(tpu,)))
        ssh_names.append(ssh)
    if not pipelined:
        tasks.append(
            Task("host-configuration",
                 sim("host-configuration",
                     SIM_SECONDS["host-configuration"]),
                 after=tuple(ssh_names))
        )
        return tasks, durations
    tasks.append(Task("host-prep",
                      sim("host-prep", SIM_SECONDS["host-prep"]),
                      after=("terraform-apply",)))
    for i in range(num_slices):
        name = f"configure-slice-{i}"
        tasks.append(Task(
            name, sim(name, SIM_SECONDS["converge-slice"]),
            after=(f"ssh-ready-slice-{i}", "host-prep"),
        ))
    return tasks, durations


def linearize(tasks: list[Task]) -> list[Task]:
    """The sequential baseline: the same tasks chained end to end in
    topological order — exactly the reference's bash `main` shape, where
    nothing starts until everything before it finished."""
    chained: list[Task] = []
    prev: str | None = None
    for task in validate(tasks):
        chained.append(
            Task(task.name, task.fn,
                 after=(prev,) if prev is not None else ())
        )
        prev = task.name
    return chained


def simulate(tasks: list[Task], clock: SimClock, max_workers: int) -> dict:
    """Run the graph on the virtual clock; return makespan + work sum."""
    timer = PhaseTimer(out=io.StringIO(), clock=clock.time, wall=clock.time)
    run_dag(
        tasks,
        max_workers=max_workers,
        timer=timer,
        on_submit=clock.launch,
        on_settled=clock.release,
    )
    return {"wall_s": timer.wall, "work_s": timer.total,
            "phases": dict(timer.durations)}


def run_benchmark(num_slices: int = 4) -> dict:
    """Sequential vs. barrier-DAG vs. pipelined provision of
    `num_slices` slices, the critical-path prediction the pipelined
    makespan must equal, and the warm no-op re-run."""
    # pool must cover the widest antichain: all slices' probes + their
    # converges + manifests/host-prep riding along terraform
    width = 3 * num_slices + 3

    seq_clock = SimClock()
    seq_tasks, _ = build_sim_tasks(seq_clock, num_slices, pipelined=False)
    sequential = simulate(linearize(seq_tasks), seq_clock, max_workers=2)

    barrier_clock = SimClock()
    barrier_tasks, _ = build_sim_tasks(
        barrier_clock, num_slices, pipelined=False
    )
    barrier = simulate(barrier_tasks, barrier_clock, max_workers=width)

    dag_clock = SimClock()
    dag_tasks, durations = build_sim_tasks(dag_clock, num_slices)
    dag = simulate(dag_tasks, dag_clock, max_workers=width)

    crit = critical_path(dag_tasks, durations)
    crit_seconds = sum(durations[name] for name in crit)
    warm = run_warm_drill(num_slices)
    return {
        "benchmark": "provision_sim",
        "metric": "provision_wall_clock_speedup",
        "unit": "x (sequential/pipelined-dag makespan, simulated)",
        "num_slices": num_slices,
        "model_seconds": dict(SIM_SECONDS),
        "sequential": sequential,
        "barrier_dag": barrier,  # the PR-2 shape: monolithic ansible
        "dag": dag,  # per-slice pipelined converge (current shape)
        "critical_path": crit,
        "critical_path_s": crit_seconds,
        "value": round(sequential["wall_s"] / dag["wall_s"], 3),
        "pipeline_vs_barrier": round(
            barrier["wall_s"] / dag["wall_s"], 3
        ),
        "dag_matches_critical_path": abs(dag["wall_s"] - crit_seconds) < 1e-6,
        "warm": warm,
    }


# --------------------------------------------------- journaled/warm drills


def build_journaled_tasks(
    clock: SimClock,
    num_slices: int,
    workdir: Path,
    executed: list,
    plan=None,
) -> tuple[list[Task], dict[str, float]]:
    """The pipelined provision DAG shape with journal metadata: each task
    sleeps its modeled duration on the virtual clock, then writes an
    artifact file — so a resume has real inputs-hashes and on-disk
    digests to verify, exactly like the live pipeline's
    tfstate/hosts.json. `plan` is a FaultPlan consulted at task START
    (kill-at-task fires before any virtual time elapses — the task dies
    with only its fsync'd `running` record, the SIGKILL signature)."""
    durations: dict[str, float] = {}
    art_dir = workdir / "artifacts"

    def sim(name: str, seconds: float, after: tuple = ()) -> Task:
        durations[name] = seconds
        artifact = art_dir / f"{name}.out"

        def fn(results: dict) -> float:
            clock.begin()
            if plan is not None:
                plan.fire(name)
            clock.sleep(seconds)
            executed.append(name)
            art_dir.mkdir(parents=True, exist_ok=True)
            artifact.write_text(f"{name}: {seconds}\n")
            return seconds

        return Task(
            name, fn, after=after,
            inputs_hash=journal_mod.inputs_hash(name, seconds),
            artifacts=(artifact,),
            restore=lambda results: durations[name],
        )

    tasks = [
        sim("terraform-apply", SIM_SECONDS["terraform-apply"]),
        sim("compile-manifests", SIM_SECONDS["compile-manifests"]),
        sim("host-prep", SIM_SECONDS["host-prep"],
            after=("terraform-apply",)),
    ]
    for i in range(num_slices):
        tpu, ssh = f"tpu-state-slice-{i}", f"ssh-ready-slice-{i}"
        tasks.append(sim(tpu, SIM_SECONDS["tpu-state-slice"],
                         after=("terraform-apply",)))
        tasks.append(sim(ssh, SIM_SECONDS["ssh-ready-slice"], after=(tpu,)))
        tasks.append(sim(f"configure-slice-{i}",
                         SIM_SECONDS["converge-slice"],
                         after=(ssh, "host-prep")))
    return tasks, durations


def _journaled_run(num_slices: int, workdir: Path, plan=None) -> dict:
    """One DAG execution against the journal at `workdir`: returns the
    executed task list, wall-clock makespan (journal-verified skips
    charged at the modeled per-task digest-check cost), and the raised
    kill (if any) — the shared leg of the crash-resume and warm drills."""
    from tritonk8ssupervisor_tpu.testing.faults import SupervisorKilled

    clock = SimClock()
    executed: list = []
    tasks, durations = build_journaled_tasks(
        clock, num_slices, workdir, executed, plan=plan
    )
    timer = PhaseTimer(out=io.StringIO(), clock=clock.time, wall=clock.time)
    journal = journal_mod.Journal(
        workdir / "journal.jsonl", echo=lambda line: None
    )
    killed = False
    with journal:
        try:
            run_dag(
                tasks,
                max_workers=3 * num_slices + 3,
                timer=timer,
                journal=journal,
                on_submit=clock.launch,
                on_settled=clock.release,
                echo=lambda line: None,
            )
        except SupervisorKilled:
            killed = True
    verified = 0
    wall = timer.wall
    if not killed:
        # every non-executed task was a journal-verified skip, which
        # costs a digest re-check — charge it to the same virtual clock
        verified = len(tasks) - len(executed)
        clock.charge(verified * SIM_SECONDS["verify-task"])
        wall = clock.time()
    return {"executed": executed, "wall_s": wall,
            "verified_skips": verified, "tasks_total": len(tasks),
            "durations": durations, "killed": killed}


def run_warm_drill(num_slices: int = 4, workdir: Path | None = None) -> dict:
    """Cold journaled run, then the warm no-op re-run: every task
    verifies against the ledger and skips — zero converge (or any other)
    tasks execute, and the warm makespan is the digest-check model, a
    small fraction of cold."""
    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-warm-drill-")
    )
    try:
        cold = _journaled_run(num_slices, root)
        warm = _journaled_run(num_slices, root)
        converges = [t for t in warm["executed"]
                     if t.startswith("configure-slice-")]
        return {
            "cold_wall_s": cold["wall_s"],
            "warm_wall_s": warm["wall_s"],
            "warm_ratio": round(warm["wall_s"] / cold["wall_s"], 4),
            "tasks_total": warm["tasks_total"],
            "warm_tasks_executed": len(warm["executed"]),
            "warm_converge_tasks_executed": len(converges),
            "verify_model_s_per_task": SIM_SECONDS["verify-task"],
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


# ------------------------------------------------------- resilience drills


def run_crash_resume_drill(
    num_slices: int = 4,
    kill_at: str = "ssh-ready-slice-1",
    workdir: Path | None = None,
) -> dict:
    """SIGKILL the supervisor mid-DAG, resume from the journal, and
    measure the redo: the resume must execute strictly fewer tasks than
    a cold run and redo < 30% of the cold run's task-seconds."""
    from tritonk8ssupervisor_tpu.testing.faults import FaultPlan, FaultRule

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-crash-drill-")
    )
    try:
        cold = _journaled_run(num_slices, root / "cold")
        cold_work = sum(cold["durations"][t] for t in cold["executed"])

        crash_dir = root / "crash"
        plan = FaultPlan(
            [FaultRule(match=f"^{kill_at}$", kill=True)],
            echo=lambda line: None,
        )
        crashed = _journaled_run(num_slices, crash_dir, plan=plan)
        assert crashed["killed"], "kill-at-task fault did not fire"

        resumed = _journaled_run(num_slices, crash_dir)
        redo_work = sum(resumed["durations"][t] for t in resumed["executed"])
        return {
            "kill_at": kill_at,
            "cold_tasks": len(cold["executed"]),
            "cold_work_s": cold_work,
            "cold_wall_s": cold["wall_s"],
            "tasks_done_before_kill": len(crashed["executed"]),
            "resumed_tasks": len(resumed["executed"]),
            "resumed_task_names": sorted(resumed["executed"]),
            "redo_work_s": redo_work,
            "mttr_wall_s": resumed["wall_s"],
            "redo_ratio": round(redo_work / cold_work, 4),
            "resume_beats_cold": resumed["wall_s"] < cold["wall_s"],
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


class _Say:
    """Minimal prompter for the drills: collect say() lines."""

    def __init__(self):
        self.lines: list = []

    def say(self, text: str = "") -> None:
        self.lines.append(text)


def run_slice_loss_drill(
    num_slices: int = 4,
    lost_slice: int = 2,
    workdir: Path | None = None,
) -> dict:
    """Lose one slice, repair it through the REAL heal path
    (provision/heal.py -> terraform -replace -> shared cache-aware
    converge -> scoped readiness) against scripted runners, and verify
    the healthy slices' tfstate entries come out byte-identical AND
    their warm converge entries survive (only the replaced slice's
    converge runs)."""
    from tritonk8ssupervisor_tpu.config.schema import ClusterConfig
    from tritonk8ssupervisor_tpu.provision import cache as cache_mod
    from tritonk8ssupervisor_tpu.provision import heal as heal_mod
    from tritonk8ssupervisor_tpu.provision.state import ClusterHosts, RunPaths

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-heal-drill-")
    )
    try:
        paths = RunPaths(root)
        paths.terraform_module("tpu-vm").mkdir(parents=True, exist_ok=True)
        config = ClusterConfig(
            project="sim-proj", zone="us-west4-a", generation="v5e",
            topology="4x4", mode="tpu-vm", num_slices=num_slices,
        )
        host_ips = [[f"10.0.{i}.1"] for i in range(num_slices)]
        internal = [[f"10.1.{i}.1"] for i in range(num_slices)]
        # one tfstate, one entry per slice — what -replace must scope over
        tfstate = {"resources": [
            {"type": "google_tpu_v2_vm", "name": "slice", "index": i,
             "ip": host_ips[i][0], "generation": 0}
            for i in range(num_slices)
        ]}
        paths.tfstate("tpu-vm").write_text(json.dumps(tfstate, indent=2))
        hosts = ClusterHosts(host_ips=[list(s) for s in host_ips],
                             internal_ips=[list(s) for s in internal],
                             coordinator_ip=internal[0][0])
        # the loss: slice's hosts vanish from the record (maintenance ate
        # the node / terraform state drifted)
        hosts.host_ips[lost_slice] = []
        hosts.internal_ips[lost_slice] = []
        hosts.save(paths.hosts_file)

        healthy_before = {
            r["index"]: json.dumps(r, sort_keys=True)
            for r in tfstate["resources"] if r["index"] != lost_slice
        }
        new_ip = f"10.9.{lost_slice}.1"
        calls: list = []

        def run(args, cwd=None, **kwargs):
            line = " ".join(str(a) for a in args)
            calls.append(line)
            if args[:2] == ["terraform", "apply"]:
                st = json.loads(paths.tfstate("tpu-vm").read_text())
                for a in args:
                    if str(a).startswith("-replace="):
                        idx = int(str(a).split("[")[1].rstrip("]"))
                        for r in st["resources"]:
                            if r["index"] == idx:
                                r["ip"] = new_ip
                                r["generation"] += 1
                paths.tfstate("tpu-vm").write_text(json.dumps(st, indent=2))
            return ""

        def run_quiet(args, cwd=None, **kwargs):
            line = " ".join(str(a) for a in args)
            calls.append(line)
            if args[:3] == ["terraform", "output", "-json"]:
                st = json.loads(paths.tfstate("tpu-vm").read_text())
                by_index = {r["index"]: r for r in st["resources"]}
                return json.dumps({
                    "host_ips": {"value": [
                        [by_index[i]["ip"]] for i in range(num_slices)
                    ]},
                    "internal_ips": {"value": [list(s) for s in internal]},
                })
            if args and args[0] == "gcloud":
                return "\n".join(
                    f"{config.node_prefix}-{i}\tREADY"
                    for i in range(num_slices)
                )
            return ""  # ssh probes / drain checks: reachable, no drain

        prompter = _Say()
        heal_mod.heal(
            config, paths, prompter, run=run, run_quiet=run_quiet,
            readiness_timeout=30.0, sleep=lambda s: None,
        )

        st_after = json.loads(paths.tfstate("tpu-vm").read_text())
        healthy_after = {
            r["index"]: json.dumps(r, sort_keys=True)
            for r in st_after["resources"] if r["index"] != lost_slice
        }
        lost_after = next(r for r in st_after["resources"]
                          if r["index"] == lost_slice)
        hosts_after = ClusterHosts.load(paths.hosts_file)
        replace_args = sorted(
            a for line in calls if line.startswith("terraform apply")
            for a in line.split() if a.startswith("-replace=")
        )
        plays = [line for line in calls
                 if line.startswith("ansible-playbook")]
        limit_used = any("--limit" in line and new_ip in line
                         for line in plays)
        cache_tasks = cache_mod.WarmCache(paths.warm_cache).tasks()
        # modeled MTTR: the heal redoes one slice's provision chain while
        # a cold redeploy pays the full pipelined critical path
        heal_model_s = (SIM_SECONDS["tpu-state-slice"]
                        + SIM_SECONDS["ssh-ready-slice"]
                        + SIM_SECONDS["converge-slice"])
        cold_model_s = (SIM_SECONDS["terraform-apply"]
                        + SIM_SECONDS["tpu-state-slice"]
                        + SIM_SECONDS["ssh-ready-slice"]
                        + SIM_SECONDS["converge-slice"])
        return {
            "lost_slice": lost_slice,
            "replace_args": replace_args,
            "scoped_to_lost_slice_only": replace_args == [
                f"-replace=google_tpu_v2_vm.slice[{lost_slice}]"
            ],
            "healthy_tfstate_untouched": healthy_before == healthy_after,
            "lost_slice_recreated": lost_after["generation"] == 1
            and lost_after["ip"] == new_ip,
            "hosts_rewritten": hosts_after.host_ips[lost_slice] == [new_ip],
            "ansible_limited_to_healed_hosts": limit_used,
            # only the replaced slice converged; its warm entry is the
            # ONLY one recorded (healthy slices were never touched)
            "ansible_runs": len(plays),
            "healed_slice_cache_recorded":
                cache_tasks == [f"configure-slice-{lost_slice}"],
            "heal_model_s": heal_model_s,
            "cold_redeploy_model_s": cold_model_s,
            "mttr_ratio": round(heal_model_s / cold_model_s, 4),
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_resilience_benchmark(num_slices: int = 4) -> dict:
    """The PR-3 acceptance datapoint: crash-resume + slice-loss drills,
    one BENCH-style JSON document."""
    crash = run_crash_resume_drill(num_slices)
    loss = run_slice_loss_drill(num_slices)
    return {
        "benchmark": "provision_resilience",
        "metric": "crash_resume_redo_ratio",
        "unit": "fraction of cold-run task seconds redone after a "
                "mid-DAG SIGKILL (target < 0.30)",
        "num_slices": num_slices,
        "model_seconds": dict(SIM_SECONDS),
        "value": crash["redo_ratio"],
        "crash_resume": crash,
        "slice_loss": loss,
        "passes": bool(
            crash["redo_ratio"] < 0.30
            and crash["resumed_tasks"] < crash["cold_tasks"]
            and loss["scoped_to_lost_slice_only"]
            and loss["healthy_tfstate_untouched"]
            and loss["ansible_runs"] == 1
        ),
    }


# ------------------------------------------------------- supervise drills


class SuperviseSim:
    """Scripted fleet for the supervisor drills (the bench twin of the
    tests' FleetSim): slice health is a function of virtual time, and a
    `terraform apply -replace` costs SIM_SECONDS['heal-slice'] on the
    clock before the slice returns (unless `heal_works=False`)."""

    def __init__(self, root: Path, clock, num_slices=4, heal_works=True):
        from tritonk8ssupervisor_tpu.config.schema import ClusterConfig
        from tritonk8ssupervisor_tpu.provision.state import (
            ClusterHosts,
            RunPaths,
        )

        self.paths = RunPaths(root)
        self.paths.terraform_module("tpu-vm").mkdir(parents=True,
                                                    exist_ok=True)
        self.config = ClusterConfig(
            project="sim-proj", zone="us-west4-a", generation="v5e",
            topology="4x4", mode="tpu-vm", num_slices=num_slices,
        )
        self.clock = clock
        self.heal_works = heal_works
        self.num_slices = num_slices
        self.down: set = set()
        self.down_at: list = []
        self.applies: list = []
        self.ips = {i: f"10.0.{i}.1" for i in range(num_slices)}
        ClusterHosts(
            host_ips=[[self.ips[i]] for i in range(num_slices)],
            internal_ips=[[f"10.1.{i}.1"] for i in range(num_slices)],
            coordinator_ip="10.1.0.1",
        ).save(self.paths.hosts_file)
        self.paths.tfstate("tpu-vm").write_text(json.dumps(
            {"resources": [{"index": i} for i in range(num_slices)]}
        ))

    def preempt(self, slice_index, at):
        self.down_at.append((at, slice_index))

    def _sync(self):
        now = self.clock.time()
        for at, i in list(self.down_at):
            if now >= at:
                self.down.add(i)
                self.down_at.remove((at, i))

    def run(self, args, cwd=None, **kwargs):
        self._sync()
        if list(args[:2]) == ["terraform", "apply"]:
            replaced = [int(str(a).split("[")[1].rstrip("]"))
                        for a in args if str(a).startswith("-replace=")]
            self.applies.append(replaced)
            self.clock.sleep(SIM_SECONDS["heal-slice"])
            if self.heal_works:
                for i in replaced:
                    self.down.discard(i)
                    self.ips[i] = f"10.9.{i}.1"
        return ""

    def run_quiet(self, args, cwd=None, **kwargs):
        from tritonk8ssupervisor_tpu.provision.runner import CommandError

        self._sync()
        if list(args[:3]) == ["terraform", "output", "-json"]:
            return json.dumps({
                "host_ips": {"value": [
                    [self.ips[i]] for i in range(self.num_slices)
                ]},
                "internal_ips": {"value": [
                    [f"10.1.{i}.1"] for i in range(self.num_slices)
                ]},
            })
        if args and args[0] == "gcloud":
            return "\n".join(
                f"{self.config.node_prefix}-{i}\tREADY"
                for i in range(self.num_slices) if i not in self.down
            )
        if args and args[0] == "ssh":
            ip = args[-2]
            index = next((i for i, x in self.ips.items() if x == ip), None)
            if "cat" in args[-1]:
                return ""
            if index in self.down:
                raise CommandError(list(args), 255)
            return ""
        return ""


def _supervise_run(world, policy, ticks, readiness_timeout=60.0):
    """Drive the supervisor as the virtual clock's single actor and
    return the replayed event ledger. The clock doubles as the actor
    hooks so parallel heal waves stay deterministic."""
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision import supervisor as sup_mod

    ledger = events_mod.EventLedger(
        world.paths.events, clock=world.clock.time, echo=lambda line: None
    )
    supervisor = sup_mod.Supervisor(
        world.config, world.paths, _Say(),
        run=world.run, run_quiet=world.run_quiet, policy=policy,
        ledger=ledger, clock=world.clock.time, sleep=world.clock.sleep,
        rng=lambda: 0.0, readiness_timeout=readiness_timeout,
        hooks=world.clock,
    )
    world.clock.begin()
    try:
        supervisor.run(ticks=ticks)
    finally:
        world.clock.release()
    return ledger.replay()


def run_supervise_mttr_drill(
    num_slices: int = 4,
    interval: float = 30.0,
    preempt_at: float = 300.0,
    workdir: Path | None = None,
) -> dict:
    """The unattended-MTTR datapoint: one slice preempted at
    `preempt_at`; the resident loop detects it (one tick), confirms it
    (the flap threshold's second tick), and heals it with ZERO human
    input. MTTR is measured preemption -> heal-done on the ledger."""
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision import supervisor as sup_mod

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-supervise-drill-")
    )
    try:
        clock = SimClock()
        world = SuperviseSim(root, clock, num_slices=num_slices)
        lost = num_slices // 2
        world.preempt(lost, at=preempt_at)
        policy = sup_mod.SupervisePolicy(interval=interval,
                                         flap_threshold=2)
        records = _supervise_run(world, policy, ticks=16)
        done = [r for r in records if r["kind"] == events_mod.HEAL_DONE]
        detected = [r for r in records
                    if r["kind"] == events_mod.VERDICT
                    and r.get("slice") == lost
                    and r.get("state") != "healthy"]
        status = json.loads(world.paths.fleet_status.read_text())
        assert world.applies == [[lost]], "expected exactly one scoped heal"
        assert status["verdict"] == "healthy", "fleet must end healthy"
        mttr = done[0]["ts"] - preempt_at
        return {
            "num_slices": num_slices,
            "interval_s": interval,
            "preempt_at_s": preempt_at,
            "lost_slice": lost,
            "detect_s": detected[0]["ts"] - preempt_at,
            "confirm_ticks": 2,  # the flap threshold
            "heal_s": done[0]["seconds"],
            "unattended_mttr_s": mttr,
            "heals_attempted": status["heals"]["attempted"],
            "heals_succeeded": status["heals"]["succeeded"],
            "end_verdict": status["verdict"],
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_supervise_breaker_drill(
    num_slices: int = 4,
    workdir: Path | None = None,
) -> dict:
    """The acceptance's second leg: a slice whose heal never sticks.
    The token bucket spaces the attempts, the breaker trips after 3
    windowed failures, and the run ENDS in degraded-hold within the
    --max-degraded budget — never a replace-loop."""
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision import supervisor as sup_mod

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-breaker-drill-")
    )
    try:
        clock = SimClock()
        world = SuperviseSim(root, clock, num_slices=num_slices,
                             heal_works=False)
        world.preempt(num_slices - 1, at=0.0)
        policy = sup_mod.SupervisePolicy(
            interval=30.0, flap_threshold=2, heal_burst=2,
            heal_refill_s=600.0, breaker_threshold=3,
            breaker_window_s=3600.0, breaker_cooldown_s=600.0,
            max_degraded=1,
        )
        records = _supervise_run(world, policy, ticks=30,
                                 readiness_timeout=60.0)
        kinds = [r["kind"] for r in records]
        status = json.loads(world.paths.fleet_status.read_text())
        return {
            "heals_attempted": kinds.count(events_mod.HEAL_START),
            "heals_failed": status["heals"]["failed"],
            "rate_limited": status["heals"]["rate_limited"],
            "held_ticks": status["heals"]["held_ticks"],
            "breaker_trips": status["breaker"]["trips"],
            "breaker_state": status["breaker"]["state"],
            "end_verdict": status["verdict"],
            "degraded": status["degraded"],
            "max_degraded": policy.max_degraded,
            "rate_limit_respected": (
                kinds.count(events_mod.HEAL_START) == len(world.applies)
                and kinds.count(events_mod.RATE_LIMITED) >= 1
            ),
            "ends_in_degraded_hold": (
                status["verdict"] == "degraded-hold"
                and len(status["degraded"]) <= policy.max_degraded
            ),
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_supervise_benchmark(num_slices: int = 4) -> dict:
    """The PR-5 acceptance datapoint, one BENCH-style JSON document:
    unattended MTTR vs. the PR-4 manual-heal baseline (which assumed an
    operator already at the keyboard — at 3am the realistic manual
    response is a page + minutes of context; the supervisor's budget is
    nonetheless judged against the OPTIMISTIC baseline plus one
    reconcile interval), plus the breaker storm drill."""
    mttr = run_supervise_mttr_drill(num_slices)
    breaker = run_supervise_breaker_drill(num_slices)
    manual_mttr = SIM_SECONDS["heal-slice"]  # operator already typing
    budget = manual_mttr + mttr["interval_s"]
    return {
        "benchmark": "provision_supervise",
        "metric": "unattended_mttr_s",
        "unit": "seconds from slice preemption to healed, zero human "
                "input (simulated; budget = manual-heal MTTR + one "
                "reconcile interval)",
        "num_slices": num_slices,
        "model_seconds": dict(SIM_SECONDS),
        "value": mttr["unattended_mttr_s"],
        "unattended_mttr_s": mttr["unattended_mttr_s"],
        "mttr": mttr,
        "manual_mttr_s": manual_mttr,
        "mttr_budget_s": budget,
        "breaker_drill": breaker,
        "passes": bool(
            mttr["unattended_mttr_s"] <= budget
            and mttr["heals_attempted"] == 1
            and breaker["ends_in_degraded_hold"]
            and breaker["rate_limit_respected"]
        ),
    }


# --------------------------------------------------------- elastic drill


class _SimTrainCkpt:
    """Duck-typed checkpoint store for the elastic drill (the
    ElasticCheckpoint surface over plain dict states)."""

    def __init__(self):
        self.store: dict = {}
        self.saves: list = []

    def latest_step(self):
        return max(self.store) if self.store else None

    def save(self, step, state, wait=False):
        self.store[step] = dict(state)
        self.saves.append(step)

    def restore(self, state, shardings, step=None):
        chosen = max(self.store) if step is None else step
        return dict(self.store[chosen])


def run_elastic_drill(
    num_slices: int = 4,
    interval: float = 30.0,
    preempt_at: float = 300.0,
    step_s: float = 1.5,
    checkpoint_every: int = 30,
    total_steps: int = 400,
    workdir: Path | None = None,
) -> dict:
    """One fault-to-training-resumed story with BOTH halves real: the
    resident supervisor (provision/supervisor.py) reconciles a scripted
    fleet on the virtual clock while a real ElasticTrainer
    (parallel/elastic.py) trains a simulated workload against the
    supervisor's actual fleet-status.json. The preemption at
    `preempt_at` kills the trainer's collective mid-step; the
    supervisor detects (one tick), confirms (flap threshold), and heals
    (SIM_SECONDS['heal-slice']); the trainer acknowledges through
    job-ack.json, waits out the heal, and resumes from its last durable
    checkpoint. Measured: steps lost (bounded by one checkpoint
    interval) and time-to-training-resumed, with the job-notified ->
    job-resumed MTTR attribution read back off the REAL event ledger."""
    import threading

    from tritonk8ssupervisor_tpu.parallel import elastic as elastic_mod
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision import supervisor as sup_mod

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-elastic-drill-")
    )
    try:
        clock = SimClock()
        world = SuperviseSim(root, clock, num_slices=num_slices)
        lost = num_slices - 1
        world.preempt(lost, at=preempt_at)
        policy = sup_mod.SupervisePolicy(interval=interval,
                                         flap_threshold=2)
        ledger = events_mod.EventLedger(
            world.paths.events, clock=clock.time, echo=lambda line: None
        )
        supervisor = sup_mod.Supervisor(
            world.config, world.paths, _Say(),
            run=world.run, run_quiet=world.run_quiet, policy=policy,
            ledger=ledger, clock=clock.time, sleep=clock.sleep,
            rng=lambda: 0.0, readiness_timeout=60.0,
        )
        sup_ticks = int(total_steps * step_s / interval) + 4

        clock.launch()

        def sup_body():
            clock.begin()
            try:
                supervisor.run(ticks=sup_ticks)
            finally:
                clock.release()

        thread = threading.Thread(target=sup_body, daemon=True)
        thread.start()

        # ---- the trainer: a modeled workload through the REAL loop
        def step_fn(state, *batch):
            clock.sleep(step_s)
            world._sync()
            if world.down:
                raise RuntimeError(
                    "collective peer lost (slice preempted)"
                )
            return {"n": state["n"] + 1}, {}

        ckpt = _SimTrainCkpt()
        trainer = elastic_mod.ElasticTrainer(
            lambda: elastic_mod.TrainSession({"n": 0}, None, step_fn),
            lambda session, i: (),
            checkpoint=ckpt,
            health=elastic_mod.FileHealthSource(world.paths.fleet_status),
            # poll cadence 17s: deliberately off the 30s tick lattice so
            # a trainer poll never lands on the same virtual instant as
            # a status publish (a same-instant read would race on thread
            # order and jitter the measured resume time)
            policy=elastic_mod.ElasticPolicy(
                checkpoint_every=checkpoint_every, poll_every=1,
                wait_base_s=17.0, wait_cap_s=17.0, max_wait_s=900.0,
                max_degraded=0,
            ),
            ack=elastic_mod.JobAck(world.paths.job_ack, clock=clock.time),
            init_fn=lambda: None, shutdown_fn=lambda: None,
            drain_fn=None,
            clock=clock.time, sleep=clock.sleep, rng=lambda: 0.0,
            echo=lambda line: None,
        )
        clock.launch()
        clock.begin()
        try:
            report = trainer.run(total_steps)
        finally:
            clock.release()
        thread.join(timeout=60)

        records = ledger.replay()
        notified = [r for r in records
                    if r["kind"] == events_mod.JOB_NOTIFIED]
        resumed = [r for r in records
                   if r["kind"] == events_mod.JOB_RESUMED]
        # the LAST resume is when training sustainably restarted
        resume = report["resumes"][-1] if report["resumes"] else {}
        time_to_resumed = (resume.get("ts", 0.0) - preempt_at
                           if resume else None)
        # budget: detect (one interval) + confirm (flap threshold's
        # second interval) + the scoped heal + the trainer's poll slack
        budget = (policy.flap_threshold * interval
                  + SIM_SECONDS["heal-slice"] + 45.0)
        return {
            "num_slices": num_slices,
            "interval_s": interval,
            "preempt_at_s": preempt_at,
            "lost_slice": lost,
            "step_s": step_s,
            "checkpoint_every_steps": checkpoint_every,
            "checkpoint_interval_s": checkpoint_every * step_s,
            "total_steps": total_steps,
            "final_step": report["final_step"],
            "steps_lost": report["steps_lost"],
            "resumes": len(report["resumes"]),
            "resume_degraded": bool(resume.get("degraded")),
            "waited_s": resume.get("waited_s"),
            "time_to_training_resumed_s": time_to_resumed,
            "budget_s": budget,
            "heal_applies": list(world.applies),
            "ledger": {
                "job_notified": len(notified),
                "job_resumed": len(resumed),
                "job_mttr_s": (resumed[0].get("mttr_s")
                               if resumed else None),
            },
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_elastic_benchmark(num_slices: int = 4) -> dict:
    """The elastic-training acceptance datapoint, one BENCH-style JSON
    document: a t=300s preemption costs at most one checkpoint interval
    of steps and training is resumed — at the healed world — within the
    detect+confirm+heal budget, with the job-notified -> job-resumed
    attribution on the event ledger."""
    drill = run_elastic_drill(num_slices)
    return {
        "benchmark": "provision_elastic",
        "metric": "time_to_training_resumed_s",
        "unit": "seconds from slice preemption to the training job "
                "stepping again (simulated; supervisor + ElasticTrainer "
                "as virtual-clock co-actors)",
        "num_slices": num_slices,
        "model_seconds": dict(SIM_SECONDS),
        "value": drill["time_to_training_resumed_s"],
        "steps_lost": drill["steps_lost"],
        "checkpoint_every_steps": drill["checkpoint_every_steps"],
        "budget_s": drill["budget_s"],
        "ledger": drill["ledger"],
        "drill": drill,
        "passes": bool(
            drill["resumes"] >= 1
            and drill["final_step"] == drill["total_steps"]
            and drill["steps_lost"] <= drill["checkpoint_every_steps"]
            and drill["time_to_training_resumed_s"] is not None
            and drill["time_to_training_resumed_s"] <= drill["budget_s"]
            and drill["heal_applies"] == [[drill["lost_slice"]]]
            and drill["ledger"]["job_notified"] >= 1
            and drill["ledger"]["job_resumed"] >= 1
        ),
    }


# ------------------------------------------------------ fleetscale drills


class FleetScaleSim(SuperviseSim):
    """SuperviseSim with per-operation counters (fleet listings, SSH
    probes) and a lock around the shared mutable state — parallel heal
    workers drive run/run_quiet from several threads at once."""

    def __init__(self, root, clock, num_slices=256, heal_works=True):
        import threading

        super().__init__(root, clock, num_slices=num_slices,
                         heal_works=heal_works)
        self.ops = {"list": 0, "ssh": 0}
        self._op_lock = threading.Lock()

    def _sync(self):
        with self._op_lock:
            super()._sync()

    def run_quiet(self, args, cwd=None, **kwargs):
        with self._op_lock:
            if args and args[0] == "gcloud" and "list" in list(args):
                self.ops["list"] += 1
            elif args and args[0] == "ssh":
                self.ops["ssh"] += 1
        return super().run_quiet(args, cwd=cwd, **kwargs)


def _tick_cost(ops: dict) -> float:
    return round(ops.get("list", 0) * SIM_SECONDS["fleet-list-page"]
                 + ops.get("ssh", 0) * SIM_SECONDS["ssh-probe"], 3)


def run_fleetscale_tick_drill(
    num_slices: int,
    ticks: int = 8,
    interval: float = 30.0,
    workdir: Path | None = None,
) -> dict:
    """Steady-state supervisor tick cost at `num_slices` on a healthy
    fleet: per-tick operation counts (windowed listing pages + SSH/drain
    probes) priced by the SIM_SECONDS model. The first tick diagnoses
    everything (never-observed slices are all dirty); steady ticks pay
    for the page refetches plus the `sweep_slices` rotation only — the
    number that must stay sublinear in N. Wall times of the real
    `tick()` call are sampled too (the tier-1 smoke pins the 256-slice
    tick under one reconcile interval)."""
    import time as wall_time

    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision import supervisor as sup_mod

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-fleetscale-tick-")
    )
    try:
        clock = SimClock()
        world = FleetScaleSim(root, clock, num_slices=num_slices)
        policy = sup_mod.SupervisePolicy(interval=interval)
        ledger = events_mod.EventLedger(
            world.paths.events, clock=clock.time, echo=lambda line: None
        )
        supervisor = sup_mod.Supervisor(
            world.config, world.paths, _Say(),
            run=world.run, run_quiet=world.run_quiet, policy=policy,
            ledger=ledger, clock=clock.time, sleep=clock.sleep,
            rng=lambda: 0.0, readiness_timeout=60.0, hooks=clock,
        )
        per_tick: list = []
        walls: list = []
        clock.begin()
        try:
            supervisor.restore()
            for _ in range(ticks):
                before = dict(world.ops)
                w0 = wall_time.perf_counter()
                supervisor.tick()
                walls.append(wall_time.perf_counter() - w0)
                per_tick.append({k: world.ops[k] - before[k]
                                 for k in world.ops})
                clock.sleep(interval)
        finally:
            clock.release()
        steady = per_tick[2:]
        steady_costs = [_tick_cost(ops) for ops in steady]
        return {
            "num_slices": num_slices,
            "interval_s": interval,
            "pages": supervisor.snapshot.page_count,
            "sweep_slices": policy.sweep_slices,
            "first_tick_ops": per_tick[0],
            "first_tick_cost_s": _tick_cost(per_tick[0]),
            "steady_ops_per_tick": steady[-1],
            "steady_tick_cost_s": round(
                sum(steady_costs) / len(steady_costs), 3
            ),
            "wall_tick_s_max": round(max(walls), 4),
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_fleetscale_outage_drill(
    num_slices: int = 256,
    lost: int = 32,
    heal_workers: int = 8,
    preempt_at: float = 300.0,
    workdir: Path | None = None,
) -> dict:
    """A zone outage at fleet scale: `lost` slices preempted at once.
    The dirty-set reconcile detects them via the changed listing pages,
    the flap filter confirms on the next tick, and the supervisor
    dispatches `lost` INDEPENDENT slice-scoped heals in waves of
    `heal_workers` — the heal makespan (first heal-start to last
    heal-done on the ledger) must be ceil(lost/workers) heal times, not
    `lost` serial ones."""
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision import supervisor as sup_mod

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-fleetscale-outage-")
    )
    try:
        clock = SimClock()
        world = FleetScaleSim(root, clock, num_slices=num_slices)
        zone = list(range(lost))  # one failure domain: slices 0..lost-1
        for i in zone:
            world.preempt(i, at=preempt_at)
        policy = sup_mod.SupervisePolicy(
            interval=30.0, flap_threshold=2, heal_burst=2,
            heal_refill_s=3600.0, heal_workers=heal_workers,
        )
        records = _supervise_run(world, policy, ticks=16,
                                 readiness_timeout=60.0)
        starts = [r for r in records
                  if r["kind"] == events_mod.HEAL_START]
        dones = [r for r in records if r["kind"] == events_mod.HEAL_DONE]
        status = json.loads(world.paths.fleet_status.read_text())
        makespan = (max(r["ts"] for r in dones)
                    - min(r["ts"] for r in starts)) if dones else None
        single_heal = SIM_SECONDS["heal-slice"]
        serial_makespan = lost * single_heal
        healed = sorted(i for r in dones for i in r["slices"])
        return {
            "num_slices": num_slices,
            "lost_slices": lost,
            "heal_workers": heal_workers,
            "preempt_at_s": preempt_at,
            "heals_attempted": len(starts),
            "heals_succeeded": len(dones),
            "scoped_per_slice": all(
                len(r["slices"]) == 1 for r in starts
            ),
            "all_healed": healed == zone,
            "heal_makespan_s": makespan,
            "single_heal_s": single_heal,
            "makespan_over_single_heal": (
                round(makespan / single_heal, 3)
                if makespan is not None else None
            ),
            "serial_makespan_s": serial_makespan,
            "parallel_speedup_x": (
                round(serial_makespan / makespan, 2)
                if makespan else None
            ),
            "unattended_mttr_s": (
                max(r["ts"] for r in dones) - preempt_at if dones else None
            ),
            "end_verdict": status["verdict"],
            "slice_states": status.get("slice_states", {}),
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_fleetscale_benchmark(
    slice_counts: tuple = (4, 64, 256),
    outage_slices: int = 256,
    outage_lost: int = 32,
) -> dict:
    """The fleet-scale acceptance datapoint, one BENCH-style JSON
    document: supervisor tick cost vs N (sublinear — the 256-slice
    steady tick within 4x the 4-slice tick via the dirty-set reconcile
    and windowed listing pages) and the zone-outage heal makespan
    (parallel slice-scoped heals: <= 4x one heal for 32 lost slices at
    8 workers, vs 32x serial)."""
    ticks = {str(n): run_fleetscale_tick_drill(n) for n in slice_counts}
    outage = run_fleetscale_outage_drill(num_slices=outage_slices,
                                         lost=outage_lost)
    small = ticks[str(min(slice_counts))]["steady_tick_cost_s"]
    big = ticks[str(max(slice_counts))]["steady_tick_cost_s"]
    ratio = round(big / small, 3) if small else None
    fleet_growth = max(slice_counts) / min(slice_counts)
    passes = bool(
        ratio is not None and ratio <= 4.0
        and outage["all_healed"]
        and outage["scoped_per_slice"]
        and outage["heal_makespan_s"] is not None
        and outage["heal_makespan_s"]
        <= 4.0 * outage["single_heal_s"] + 1e-6
        and outage["end_verdict"] == "healthy"
        and ticks[str(max(slice_counts))]["steady_tick_cost_s"]
        <= ticks[str(max(slice_counts))]["interval_s"]
    )
    return {
        "benchmark": "provision_fleetscale",
        "metric": "steady_tick_cost_ratio_256_over_4",
        "unit": "x (modeled steady-state supervisor tick cost at 256 "
                "slices over 4 slices; 64x the fleet must cost <= 4x "
                "the tick — sublinear via dirty-set reconcile + paged "
                "listings)",
        "model_seconds": dict(SIM_SECONDS),
        "value": ratio,
        "fleet_growth_x": fleet_growth,
        "ticks": ticks,
        "outage": outage,
        "passes": passes,
    }


# --------------------------------------------------------- chaos campaigns


def run_chaos_blast_radius_drill(
    num_slices: int = 256,
    failure_domains: int = 8,
    lost_domain_index: int = 3,
    preempt_at: float = 300.0,
    heal_workers: int = 8,
    workdir: Path | None = None,
) -> dict:
    """THE blast-radius acceptance drill: a seeded domain outage kills
    one whole failure domain (32 of 256 slices) while two unrelated
    slices die in HEALTHY domains. The supervisor must classify the
    correlated loss (DOMAIN_OUTAGE), open the per-domain breaker for
    the outaged domain ONLY, keep heals flowing in the healthy domains
    meanwhile, re-enter the dead domain via exactly ONE canary heal,
    and drain the rest in parallel waves — with the InvariantChecker
    finding zero violations in the ledger."""
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.testing import chaos

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-chaos-blast-")
    )
    try:
        config = chaos.sim_config(num_slices, failure_domains)
        lost_domain = config.domain_of(lost_domain_index)
        domain_slices = sorted(
            i for i, d in config.domain_map().items() if d == lost_domain
        )
        # two unrelated losses in OTHER domains prove heals keep flowing
        healthy_losses = [
            i for i in range(num_slices)
            if config.domain_of(i) != lost_domain
        ][:2]
        scenario = chaos.Scenario(
            seed=0, num_slices=num_slices,
            failure_domains=failure_domains,
            events=[
                {"kind": "domain-outage", "domain": lost_domain,
                 "at": preempt_at},
                {"kind": "preemption-storm", "slices": healthy_losses,
                 "at": preempt_at},
            ],
            max_ticks=80, mttr_bound_s=2400.0,
        )
        policy = chaos.default_policy()
        policy.heal_workers = heal_workers
        policy.heal_refill_s = 36_000.0
        policy.page_size = 64
        result = chaos.run_campaign(scenario, root, policy=policy)
        records = events_mod.EventLedger(
            chaos.RunPaths(root).events
        ).replay()
        outage_domains = sorted({
            r["domain"] for r in records
            if r["kind"] == events_mod.DOMAIN_OUTAGE
        })
        breaker_open_domains = sorted({
            r["domain"] for r in records
            if r["kind"] == events_mod.DOMAIN_BREAKER_OPEN
        })
        canary_starts = [r for r in records
                        if r["kind"] == events_mod.HEAL_START
                        and r.get("canary")]
        closes = [r for r in records
                  if r["kind"] == events_mod.DOMAIN_BREAKER_CLOSE
                  and r.get("domain") == lost_domain]
        gate_lift_ts = closes[0]["ts"] if closes else None
        healthy_domain_dones = [
            r for r in records if r["kind"] == events_mod.HEAL_DONE
            if set(r["slices"]) & set(healthy_losses)
        ]
        heals_flowed_during_hold = bool(
            healthy_domain_dones and gate_lift_ts is not None
            and all(r["ts"] < gate_lift_ts for r in healthy_domain_dones)
        )
        dones = [r for r in records if r["kind"] == events_mod.HEAL_DONE]
        healed = sorted({i for r in dones for i in r["slices"]})
        domain_mttr = (
            max(r["ts"] for r in dones) - preempt_at if dones else None
        )
        return {
            "num_slices": num_slices,
            "failure_domains": failure_domains,
            "lost_domain": lost_domain,
            "lost_slices": len(domain_slices),
            "healthy_domain_losses": healthy_losses,
            "heal_workers": heal_workers,
            "preempt_at_s": preempt_at,
            "outage_classified_domains": outage_domains,
            "breaker_open_domains": breaker_open_domains,
            "breaker_open_only_lost_domain":
                breaker_open_domains == [lost_domain],
            "heals_flowed_in_healthy_domains": heals_flowed_during_hold,
            "canary_heals": len(canary_starts),
            "exactly_one_canary": len(canary_starts) == 1,
            "all_healed": healed == sorted(domain_slices + healthy_losses),
            "blast_radius_mttr_s": domain_mttr,
            "violations": result["violations"],
            "converged": result["converged"],
            "restarts": result["restarts"],
        }
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_chaos_campaigns(
    campaigns: int = 25,
    num_slices: int = 16,
    failure_domains: int = 4,
    seed0: int = 1,
) -> dict:
    """N seeded campaigns (testing/chaos.py): every one must converge
    with ZERO InvariantChecker violations; the MTTR distribution is the
    perf metric the --check gate watches."""
    from tritonk8ssupervisor_tpu.testing import chaos

    results: list = []
    for seed in range(seed0, seed0 + campaigns):
        scenario = chaos.generate_scenario(
            seed, num_slices=num_slices, failure_domains=failure_domains
        )
        root = Path(tempfile.mkdtemp(prefix="tk8s-chaos-camp-"))
        try:
            results.append(chaos.run_campaign(scenario, root))
        finally:
            shutil.rmtree(root, ignore_errors=True)
    mttrs = [r["mttr_s"] for r in results if r["mttr_s"] is not None]
    violations = [v for r in results for v in r["violations"]]
    return {
        "campaigns": campaigns,
        "seed_range": [seed0, seed0 + campaigns - 1],
        "num_slices": num_slices,
        "failure_domains": failure_domains,
        "converged": sum(1 for r in results if r["converged"]),
        "violations": violations,
        "violation_count": len(violations),
        "mttr_mean_s": (round(sum(mttrs) / len(mttrs), 1)
                        if mttrs else None),
        "mttr_max_s": max(mttrs) if mttrs else None,
        "restarts": sum(r["restarts"] for r in results),
        "domain_outages": sum(r["domain_outages"] for r in results),
        "canaries": sum(r["canaries"] for r in results),
        "heals_deferred": sum(r["heals_deferred"] for r in results),
        "per_seed": [
            {"seed": r["seed"], "events": r["events"],
             "mttr_s": r["mttr_s"], "violations": len(r["violations"])}
            for r in results
        ],
    }


def run_chaos_benchmark(campaigns: int = 25) -> dict:
    """The blast-radius acceptance datapoint, one BENCH-style JSON
    document: the 32-of-256 domain-outage drill (heals keep flowing in
    healthy domains, one canary gates re-entry) plus `campaigns` seeded
    chaos campaigns with zero ledger-invariant violations."""
    blast = run_chaos_blast_radius_drill()
    sweep = run_chaos_campaigns(campaigns=campaigns)
    return {
        "benchmark": "provision_chaos",
        "metric": "campaign_mttr_mean_s",
        "unit": "seconds from first injected fault to fleet healthy, "
                "averaged over seeded chaos campaigns (simulated; every "
                "campaign must pass the ledger InvariantChecker with "
                "zero violations)",
        "model_seconds": dict(SIM_SECONDS),
        "value": sweep["mttr_mean_s"],
        "blast_radius": blast,
        "campaigns": sweep,
        "passes": bool(
            blast["breaker_open_only_lost_domain"]
            and blast["heals_flowed_in_healthy_domains"]
            and blast["exactly_one_canary"]
            and blast["all_healed"]
            and not blast["violations"]
            and sweep["converged"] == sweep["campaigns"]
            and sweep["violation_count"] == 0
        ),
    }


# --------------------------------------------------------- serving drills


def _serve_status_doc(now, num_slices, generation, down=(), draining=(),
                      healing=False, shed=False):
    """A fleet-status document with the blocks the gateway routes on
    (membership + serving), shaped like events.fleet_status emits it.
    The bench scripts the SUPERVISOR side deterministically; the
    gateway consumes the real file through the real reader — the
    contract under test is the read side."""
    down = sorted(down)
    draining = sorted(draining)
    degraded = sorted(set(down) | set(draining))
    avoid = {str(i): "missing" for i in down}
    avoid.update({str(i): "draining" for i in draining})
    verdict = "degraded-hold" if shed else (
        "recovering" if healing else
        ("degraded" if degraded else "healthy")
    )
    return {
        "v": 1,
        "updated": now,
        "verdict": verdict,
        "slices_total": num_slices,
        "membership": {"generation": generation,
                       "heal_in_progress": healing,
                       "draining": draining},
        "degraded": degraded,
        "serving": {
            "eligible": [i for i in range(num_slices)
                         if i not in set(degraded)],
            "avoid": avoid,
            "shed": shed,
        },
    }


def run_serve_scenario(
    num_slices: int = 4,
    slots: int = 8,
    prefill_chunk: int = 64,
    duration_s: float = 1200.0,
    base_rps: float = 7.0,
    diurnal_amplitude: float = 0.3,
    bursts: tuple = (),
    outage: dict | None = None,
    shed_window: tuple | None = None,
    queue_budget: int = 64,
    seed: int = 11,
    workdir: Path | None = None,
    deadline_s: float | None = None,
    with_reqlog: bool = False,
    page_size: int = 16,
    pages_per_slice: int | None = None,
    prefix_cache: bool = False,
    shared_prefix_len: int = 0,
    shared_prefix_share: float = 0.0,
    prompt_lens: tuple | None = None,
    with_telemetry: bool = False,
    spec_k: int = 0,
    spec_acceptance: float = 0.85,
) -> dict:
    """One open-loop traffic drive against the gateway on a virtual
    clock. `slots=1` + whole-bucket prefill IS the request-at-a-time
    baseline — same gateway, same queue, same SLO budget, only the
    batching differs, so the comparison isolates continuous batching.

    `outage={"slice": i, "at": t, "detect_s": d, "heal_s": h}` scripts
    a mid-run slice loss: the engine dies at t (its in-flight freezes —
    exactly a preemption's exposure), the supervisor's status reports
    the loss at t+d with a membership generation bump (the gateway
    requeues the frozen work and routes around), and the heal lands at
    t+d+h (eligible again, generation bumps back up). `shed_window=
    (t0, t1)` scripts a breaker-open hold instead.

    The engine-hot-path knobs mirror serving/engine.SlotEngine:
    `pages_per_slice` bounds each modeled engine's page pool (None =
    unbounded accounting, the pre-paging behavior), `prefix_cache`
    turns cross-request prefix reuse on, and `shared_prefix_len` /
    `shared_prefix_share` shape the traffic (serving/traffic.py) so a
    share of arrivals opens with the same system prompt.

    `with_telemetry` wires the obs/ plane (registry + span log in the
    workdir, flush mode) — the --obs overhead gate drives the SAME
    scenario with and without it and compares `drive_wall_s`, the
    measured wall-clock of the virtual-time drive (pure Python: the
    virtual clock never sleeps, so the wall difference IS the
    instrumentation cost)."""
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision.fleetview import FileHealthSource
    from tritonk8ssupervisor_tpu.serving import gateway as gw_mod
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod
    from tritonk8ssupervisor_tpu.serving import traffic as traffic_mod

    own_tmp = workdir is None
    root = Path(workdir) if workdir is not None else Path(
        tempfile.mkdtemp(prefix="tk8s-serve-drill-")
    )
    try:
        status_path = root / "fleet-status.json"
        cost = gw_mod.DecodeCostModel()
        policy = gw_mod.GatewayPolicy(
            max_seq_len=512,
            slots_per_slice=slots,
            prefill_chunk=prefill_chunk,
            queue_budget=queue_budget,
            bucket_bounds=(64, 128, 256),
            poll_every_s=1.0,
            default_deadline_s=deadline_s,
            page_size=page_size,
            pages_per_slice=pages_per_slice,
            prefix_cache=prefix_cache,
            spec_k=spec_k,
            spec_acceptance=spec_acceptance,
        )
        clock = SimClock()
        engines = {
            i: gw_mod.ModeledEngine(slots=slots,
                                    prefill_chunk=prefill_chunk,
                                    cost=cost,
                                    page_size=page_size,
                                    num_pages=pages_per_slice,
                                    prefix_cache=prefix_cache,
                                    spec_k=spec_k,
                                    spec_acceptance=spec_acceptance)
            for i in range(num_slices)
        }
        # fsync=False: the virtual-clock drive never crashes the OS,
        # only in-memory objects — the fsync path is pinned in the
        # reqlog unit tests and exercised by `./setup.sh serve`
        reqlog = (reqlog_mod.RequestLog(root / "serve-requests.jsonl",
                                        clock=clock.time,
                                        echo=lambda line: None,
                                        fsync=False)
                  if with_reqlog else None)
        telemetry = None
        if with_telemetry:
            from tritonk8ssupervisor_tpu import obs as obs_lib

            telemetry = obs_lib.Telemetry(
                obs_lib.MetricsRegistry(clock=clock.time),
                obs_lib.Tracer(
                    obs_lib.SpanLog(root / "telemetry-spans.jsonl",
                                    clock=clock.time,
                                    echo=lambda line: None, fsync=False),
                    plane=obs_lib.SERVING, clock=clock.time,
                ),
            )
        gateway = gw_mod.Gateway(
            engines, FileHealthSource(status_path), policy=policy,
            clock=clock.time, reqlog=reqlog, telemetry=telemetry,
        )
        traffic_kwargs = dict(
            base_rps=base_rps, diurnal_amplitude=diurnal_amplitude,
            diurnal_period_s=600.0, bursts=tuple(bursts), seed=seed,
            deadline_s=deadline_s,
            key_prefix=(f"s{seed}" if with_reqlog else None),
            shared_prefix_len=shared_prefix_len,
            shared_prefix_share=shared_prefix_share,
        )
        if prompt_lens is not None:
            traffic_kwargs["prompt_lens"] = tuple(prompt_lens)
        model = traffic_mod.TrafficModel(**traffic_kwargs)
        arrivals = traffic_mod.generate_arrivals(model, duration_s)

        def write_status(**kwargs):
            def fn(_gateway):
                events_mod.write_fleet_status(
                    status_path,
                    _serve_status_doc(clock.time(), num_slices, **kwargs),
                )
            return fn

        events: list = [traffic_mod.WorldEvent(0.0, write_status(
            generation=1))]
        window = None
        if outage is not None:
            lost = outage["slice"]
            t0 = outage["at"]
            t_detect = t0 + outage.get("detect_s", 30.0)
            t_heal = t_detect + outage.get("heal_s", 120.0)
            window = (t0, t_heal)
            events += [
                traffic_mod.WorldEvent(
                    t0, lambda g: g.workers[lost].fail()),
                traffic_mod.WorldEvent(t_detect, write_status(
                    generation=2, down=(lost,), healing=True)),
                traffic_mod.WorldEvent(
                    t_heal, lambda g: g.workers[lost].revive()),
                traffic_mod.WorldEvent(t_heal, write_status(
                    generation=3)),
            ]
        if shed_window is not None:
            t0, t1 = shed_window
            window = (t0, t1)
            events += [
                traffic_mod.WorldEvent(t0, write_status(
                    generation=1, shed=True)),
                traffic_mod.WorldEvent(t1, write_status(generation=1)),
            ]

        wall_t0 = time.perf_counter()
        clock.begin()
        try:
            report = traffic_mod.drive_open_loop(
                gateway, arrivals, clock, duration_s, events=tuple(events),
            )
        finally:
            clock.release()
        drive_wall_s = time.perf_counter() - wall_t0

        chips = num_slices * cost.chips_per_slice
        span = max(duration_s, report["drive_end_s"])
        tokens = report["tokens_generated"]
        m = gateway.metrics
        sheds = [r for r in m.rejected
                 if r["reason"] in (gw_mod.REJECT_OVERLOAD,
                                    gw_mod.REJECT_BREAKER,
                                    gw_mod.REJECT_NO_CAPACITY)]
        shed_slack = 120.0
        sheds_outside_window = (
            [r for r in sheds
             if not (window[0] <= r["ts"] <= window[1] + shed_slack)]
            if window is not None else list(sheds)
        )
        overload_without_depth = [
            r for r in sheds
            if r["reason"] == gw_mod.REJECT_OVERLOAD
            and r["depth"] < queue_budget
        ]
        result = {
            "num_slices": num_slices,
            "chips": chips,
            "slots_per_slice": slots,
            "prefill_chunk": prefill_chunk,
            "duration_s": duration_s,
            "offered_requests": report["offered"],
            "completed": report["completed"],
            "rejected": report["rejected"],
            "requeued_after_slice_loss":
                report["requeued_after_slice_loss"],
            "tokens_generated": tokens,
            "tokens_per_sec": round(tokens / span, 3),
            "tokens_per_sec_per_chip": round(tokens / span / chips, 3),
            "p50_latency_s": report["p50_latency_s"],
            "p99_latency_s": report["p99_latency_s"],
            "max_queue_depth": report["max_queue_depth"],
            "final_queue_depth": report["final_queue_depth"],
            "quiescent": report["quiescent"],
            "sheds": len(sheds),
            "sheds_outside_demand_window": len(sheds_outside_window),
            "overload_sheds_below_budget": len(overload_without_depth),
            "expired": report["expired"],
            "deadline_s": deadline_s,
            "journaled": with_reqlog,
            "telemetry": with_telemetry,
            "drive_wall_s": round(drive_wall_s, 4),
        }
        engine = report.get("engine")
        if engine is not None:
            # the paged-KV/prefix observability block (per-slice detail
            # dropped: the bench JSON stays bounded) plus the derived
            # "how much of the shared prefix re-prefilled on hits"
            # metric — ~0 is the acceptance bar
            summary = {k: v for k, v in engine.items()
                       if k != "per_slice"}
            prefix = engine.get("prefix")
            if prefix is not None and shared_prefix_share > 0:
                aligned = (shared_prefix_len // page_size) * page_size
                offered_on_hits = prefix["hits"] * aligned
                summary["shared_prefix_reprefilled_on_hits"] = (
                    offered_on_hits - prefix["hit_tokens"]
                )
                summary["shared_prefix_aligned_tokens"] = aligned
            result["engine"] = summary
            result["shared_prefix_len"] = shared_prefix_len
            result["shared_prefix_share"] = shared_prefix_share
            result["pages_per_slice"] = pages_per_slice
            result["prefix_cache"] = prefix_cache
            result["spec_k"] = spec_k
        if outage is not None:
            t0, t_heal = window
            in_window = [r for r in m.completed
                         if r.done_at is not None
                         and t0 <= r.done_at <= t_heal]
            goodput = sum(r.generated for r in in_window) / (t_heal - t0)
            pre = [r for r in m.completed
                   if r.done_at is not None and r.done_at < t0]
            nominal = (sum(r.generated for r in pre) / t0) if pre else None
            result.update({
                "outage": dict(outage),
                "outage_window_s": [t0, t_heal],
                "goodput_tokens_per_sec_during_outage": round(goodput, 3),
                "nominal_tokens_per_sec_before_outage":
                    round(nominal, 3) if nominal else None,
                "goodput_over_nominal": (
                    round(goodput / nominal, 4) if nominal else None
                ),
            })
        if shed_window is not None:
            t0, t1 = window
            accepted_in_window = [
                ts for ts, _rid in m.accepted if t0 <= ts < t1
            ]
            breaker_rejects = [r for r in m.rejected
                               if r["reason"] == gw_mod.REJECT_BREAKER]
            result.update({
                "shed_window_s": [t0, t1],
                "breaker_rejects": len(breaker_rejects),
                "breaker_rejects_inside_window": len(
                    [r for r in breaker_rejects if t0 <= r["ts"] < t1]
                ),
                # depth_samples record enqueues; any inside the hold
                # means the breaker gate leaked an admission
                "admitted_during_hold": len(accepted_in_window),
            })
        return result
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def run_serve_benchmark(num_slices: int = 4) -> dict:
    """The serving-gateway acceptance datapoint, one BENCH-style JSON
    document. Four drives of the SAME open-loop arrival stream:

    - request-at-a-time (slots=1, whole-bucket prefill): the baseline;
    - continuous batching (8 slots, chunked prefill): must sustain
      >= 2x the baseline's tokens/sec at equal or better p99;
    - continuous + a mid-run slice outage (detect 30 s, heal 120 s —
      the PR-5 unattended-MTTR shape): the gateway requeues the lost
      slice's in-flight work, routes around it, sheds only while the
      SLO budget demands, and drains back to quiescent;
    - a breaker-open hold: every request inside the window refused
      429-style with retry-after, zero admissions leak through.

    Since the request-plane resilience PR, every drive runs WITH the
    request journal attached and a 300 s default deadline — the
    PR-9 numbers must hold with the durability machinery on (the
    deadline is sized so it never binds under healthy drainage;
    `expired` must stay 0 in the continuous drive).

    The engine-hot-path PR adds two more comparisons:

    - **shared-prefix A/B** (the prefix/KV-cache-reuse headline):
      shared-system-prompt traffic (60 % of arrivals open with the
      same 192-token system prompt) served cold (no prefix cache, the
      8-slot PR-9 engine) vs warm (prefix cache + paged slots at 16
      slots on a MEMORY-EQUAL page pool). The warm drive must sustain
      >= 1.5x the `continuous` drive's tokens/sec/chip — the committed
      PR-9 configuration is the baseline the acceptance names — and
      re-prefill ~0 of the shared prefix on cache hits.
    - **paged-slots A/B** (memory-equal): a mixed short/long trace
      served by the fixed 8-slot engine vs 16 paged slots whose page
      pool holds EXACTLY what the dense 8 x max_len cache held
      (8 * 512 / 16 = 256 pages). Paged must raise effective
      slots-per-slice above the fixed 8 (peak_slots_busy) and
      throughput with it — prefix cache OFF here, so the comparison
      isolates paging.
    """
    common = dict(num_slices=num_slices, duration_s=1200.0,
                  base_rps=7.0, queue_budget=64, seed=11,
                  deadline_s=300.0, with_reqlog=True)
    rat = run_serve_scenario(slots=1, prefill_chunk=256, **common)
    cont = run_serve_scenario(
        slots=8, prefill_chunk=64,
        bursts=((300.0, 60.0, 1.6), (800.0, 60.0, 1.6)), **common
    )
    # ---- shared-prefix A/B: same traffic, only the cache differs.
    # Load is sized ABOVE what the cold engine can prefill+decode (the
    # millions-of-users shape: every request re-prefilling a 192-token
    # system prompt costs 3 extra chunks/request) and WITHIN what the
    # warm engine sustains — the speedup is prefix-skip + the paged
    # slots it frees, not a lighter workload.
    shared_common = dict(
        num_slices=num_slices, duration_s=1200.0, base_rps=13.0,
        diurnal_amplitude=0.2, queue_budget=96, seed=11,
        deadline_s=300.0, with_reqlog=True, page_size=16,
        shared_prefix_len=192, shared_prefix_share=0.6,
        prompt_lens=(208, 224, 240, 256),
    )
    shared_cold = run_serve_scenario(
        slots=8, prefill_chunk=64, prefix_cache=False,
        pages_per_slice=None, **shared_common
    )
    shared_warm = run_serve_scenario(
        slots=16, prefill_chunk=64, prefix_cache=True,
        pages_per_slice=256, **shared_common
    )
    # ---- paged-slots A/B: mixed short/long trace, memory-equal pools
    mixed_common = dict(
        num_slices=num_slices, duration_s=1200.0, base_rps=12.0,
        diurnal_amplitude=0.2, queue_budget=96, seed=11,
        deadline_s=300.0, with_reqlog=True, page_size=16,
    )
    paged_fixed = run_serve_scenario(
        slots=8, prefill_chunk=64, prefix_cache=False,
        pages_per_slice=None, **mixed_common
    )
    paged = run_serve_scenario(
        slots=16, prefill_chunk=64, prefix_cache=False,
        pages_per_slice=256, **mixed_common
    )
    # ---- speculative A/B (the engine-speed headline): the same
    # open-loop stream on the SAME memory-equal paged pool, with and
    # without a drafter. Load is sized ABOVE both arms' capacity
    # (~667 vs ~1370 modeled tok/s at 4 slices), so each arm saturates
    # and the ratio measures per-chip CAPACITY — the matched-memory
    # spec-vs-paged-baseline comparison the acceptance bar names. The
    # modeled engine mirrors the real SlotEngine's token accounting
    # with seeded per-request acceptance draws at 0.85.
    spec_common = dict(
        num_slices=num_slices, duration_s=600.0, base_rps=30.0,
        diurnal_amplitude=0.2, queue_budget=96, seed=11,
        deadline_s=300.0, with_reqlog=True, page_size=16,
        pages_per_slice=256, prefix_cache=False,
    )
    spec_base = run_serve_scenario(slots=8, prefill_chunk=64,
                                   **spec_common)
    spec_drive = run_serve_scenario(slots=8, prefill_chunk=64,
                                    spec_k=4, spec_acceptance=0.85,
                                    **spec_common)
    # load chosen to sit BETWEEN (N-1)- and N-slice capacity during
    # the outage window (which rides the diurnal high): losing one
    # slice makes the SLO budget bind (sheds must appear) and the heal
    # makes it stop binding (sheds must stop) — both directions of
    # "sheds only while demanded" are exercised, not vacuous. Modeled
    # capacity: ~612 tok/s at 4 slices, ~458 at 3 (the saturation
    # probe); offered rides 398..538 tok/s, so the budget binds ONLY
    # while the fleet is a slice short.
    outage = run_serve_scenario(
        slots=8, prefill_chunk=64, base_rps=9.0,
        diurnal_amplitude=0.15,
        duration_s=1200.0, num_slices=num_slices, queue_budget=64,
        seed=11, deadline_s=300.0, with_reqlog=True,
        outage={"slice": 2, "at": 690.0, "detect_s": 30.0,
                "heal_s": 120.0},
    )
    breaker = run_serve_scenario(
        slots=8, prefill_chunk=64, base_rps=2.0, duration_s=360.0,
        num_slices=num_slices, queue_budget=64, seed=11,
        deadline_s=300.0, with_reqlog=True,
        shed_window=(120.0, 240.0),
    )
    speedup = (round(cont["tokens_per_sec"] / rat["tokens_per_sec"], 3)
               if rat["tokens_per_sec"] else None)
    prefix_speedup = (
        round(shared_warm["tokens_per_sec"]
              / shared_cold["tokens_per_sec"], 3)
        if shared_cold["tokens_per_sec"] else None
    )
    # the acceptance bar names the committed PR-9 configuration — the
    # `continuous` drive IS that configuration, re-run on this stream
    warm_over_pr9 = (
        round(shared_warm["tokens_per_sec_per_chip"]
              / cont["tokens_per_sec_per_chip"], 3)
        if cont["tokens_per_sec_per_chip"] else None
    )
    warm_prefix = (shared_warm.get("engine") or {}).get("prefix") or {}
    reprefilled = (shared_warm.get("engine") or {}).get(
        "shared_prefix_reprefilled_on_hits")
    aligned = (shared_warm.get("engine") or {}).get(
        "shared_prefix_aligned_tokens") or 0
    paged_peak = (paged.get("engine") or {}).get("peak_slots_busy")
    fixed_peak = (paged_fixed.get("engine") or {}).get("peak_slots_busy")
    spec_over_paged = (
        round(spec_drive["tokens_per_sec_per_chip"]
              / spec_base["tokens_per_sec_per_chip"], 3)
        if spec_base["tokens_per_sec_per_chip"] else None
    )
    spec_engine_stats = (spec_drive.get("engine") or {}).get("spec") or {}
    spec_acceptance = spec_engine_stats.get("acceptance_rate")
    passes = bool(
        speedup is not None and speedup >= 2.0
        and cont["p99_latency_s"] is not None
        and rat["p99_latency_s"] is not None
        and cont["p99_latency_s"] <= rat["p99_latency_s"]
        and cont["quiescent"]
        and cont["overload_sheds_below_budget"] == 0
        # with journaling + deadlines enabled the 300s budget must not
        # bind under healthy drainage — an expiry here means the
        # deadline machinery cost throughput it had no right to
        and cont["expired"] == 0
        # outage: bounded tail, no stranded work, sheds only while the
        # lost capacity makes the budget demand it, goodput holds
        and outage["quiescent"]
        and outage["requeued_after_slice_loss"] > 0
        and outage["p99_latency_s"] is not None
        and outage["p99_latency_s"] <= 60.0
        and outage["sheds_outside_demand_window"] == 0
        and outage["overload_sheds_below_budget"] == 0
        and (outage["goodput_over_nominal"] or 0) >= 0.5
        # breaker: the hold is absolute and bounded to the window
        and breaker["admitted_during_hold"] == 0
        and breaker["breaker_rejects"] > 0
        and breaker["breaker_rejects"]
        == breaker["breaker_rejects_inside_window"]
        and breaker["quiescent"]
        # shared-prefix: warm sustains >= 1.5x the PR-9 per-chip
        # number, the cache actually hits, and the shared prefix
        # re-prefills ~0 tokens on hits (< 2% of what hits offered)
        and warm_over_pr9 is not None and warm_over_pr9 >= 1.5
        and prefix_speedup is not None and prefix_speedup > 1.0
        and (warm_prefix.get("hit_rate") or 0) >= 0.4
        and reprefilled is not None
        and reprefilled
        <= 0.02 * max(1, warm_prefix.get("hits", 0) * aligned)
        and shared_warm["quiescent"]
        and shared_warm["overload_sheds_below_budget"] == 0
        and shared_warm["expired"] == 0
        # paged slots: memory-equal pool, effective concurrency above
        # the fixed-cache 8, and the throughput to show for it
        and paged_peak is not None and paged_peak > 8
        and paged["tokens_per_sec"] > paged_fixed["tokens_per_sec"]
        and paged["quiescent"]
        and paged["overload_sheds_below_budget"] == 0
        # speculative: >= 1.4x per-chip over the paged baseline at
        # matched KV memory, no worse p99, honest sheds, acceptance
        # actually near the modeled 0.85 (the seeded draws work)
        and spec_over_paged is not None and spec_over_paged >= 1.4
        and spec_drive["p99_latency_s"] is not None
        and spec_base["p99_latency_s"] is not None
        and spec_drive["p99_latency_s"] <= spec_base["p99_latency_s"]
        and spec_drive["quiescent"] and spec_base["quiescent"]
        and spec_drive["overload_sheds_below_budget"] == 0
        and spec_drive["expired"] == 0
        # accepted/drafted under LEADING-RUN semantics at per-token
        # acceptance a=0.85, k=4 is (a + a^2 + a^3 + a^4)/4 ~ 0.677,
        # not 0.85 — a reject truncates the rest of the draft
        and spec_acceptance is not None
        and 0.62 <= spec_acceptance <= 0.73
    )
    return {
        "benchmark": "serving_gateway",
        "metric": "continuous_over_request_at_a_time_tokens_per_sec",
        "unit": "x (same open-loop arrival stream, same SLO budget; "
                "simulated on the decode cost model — continuous "
                "batching must sustain >= 2x at equal or better p99)",
        "num_slices": num_slices,
        "value": speedup,
        "tokens_per_sec_per_chip": cont["tokens_per_sec_per_chip"],
        "p99_latency_s": cont["p99_latency_s"],
        "request_at_a_time": rat,
        "continuous": cont,
        "outage": outage,
        "breaker": breaker,
        "shared_prefix": {
            "metric": "warm_over_pr9_tokens_per_sec_per_chip",
            "unit": "x (60% of arrivals share a 192-token system "
                    "prompt; warm = prefix cache + 16 paged slots on "
                    "a memory-equal pool vs the committed PR-9 8-slot "
                    "configuration — >= 1.5x is the acceptance bar)",
            "value": warm_over_pr9,
            "prefix_speedup_warm_over_cold": prefix_speedup,
            "cold": shared_cold,
            "warm": shared_warm,
        },
        "paged_slots": {
            "metric": "effective_slots_per_slice",
            "unit": "slots (peak busy; mixed short/long trace on a "
                    "memory-equal page pool — 16 paged slots in the "
                    "HBM the dense cache spent on 8)",
            "value": paged_peak,
            "fixed_peak_slots_busy": fixed_peak,
            "fixed": paged_fixed,
            "paged": paged,
        },
        "speculative": {
            "metric": "spec_over_paged_baseline_tokens_per_sec_per_chip",
            "unit": "x (same saturating open-loop stream on the same "
                    "memory-equal paged pool; spec = drafter k=4 at "
                    "modeled acceptance 0.85, seeded per-request "
                    "draws — >= 1.4x per chip at no worse p99 is the "
                    "acceptance bar)",
            "value": spec_over_paged,
            "spec_k": 4,
            "acceptance_rate": spec_acceptance,
            # greedy token-identity is the REAL engine's property —
            # pinned in BENCH_engine.json's speculative block (which
            # --check verifies structurally) and tests/test_spec.py;
            # this modeled block mirrors the token ACCOUNTING only
            "baseline": spec_base,
            "spec": spec_drive,
        },
        "passes": passes,
    }


def run_serve_chaos_benchmark(campaigns: int = 25) -> dict:
    """The request-plane resilience acceptance datapoint, one
    BENCH-style JSON document:

    - N seeded supervisor+gateway campaigns (testing/chaos.py
      `run_serve_campaign`): a REAL Supervisor reconciling a scripted
      world and a REAL Gateway serving seeded open-loop traffic with
      deadlines + idempotency keys as co-actors on one SimClock, every
      campaign's request journal and event ledger folded through the
      ServeInvariantChecker — request conservation, no double-service,
      deadline honesty, honest Retry-After, bounded view staleness,
      cross-ledger consistency. Zero violations is the bar.
    - the gateway SIGKILL drill (`run_gateway_kill_drill`): a crash
      mid-dispatch must lose ZERO accepted requests — incomplete work
      re-admitted front-of-queue from the journal, duplicates of
      completed keys answered from the recorded result — with
      restart-to-first-token MTTR as the headline metric.
    """
    from tritonk8ssupervisor_tpu.testing import chaos

    results: list = []
    violations: list = []
    with tempfile.TemporaryDirectory(prefix="tk8s-servechaos-") as tmp:
        for seed in range(1, campaigns + 1):
            scenario = chaos.generate_serve_scenario(seed)
            out = chaos.run_serve_campaign(
                scenario, Path(tmp) / f"seed-{seed}"
            )
            results.append(out)
            violations += [f"seed {seed}: {v}"
                           for v in out["violations"]]
        kill = chaos.run_gateway_kill_drill(Path(tmp) / "kill-drill")
    violations += [f"kill-drill: {v}" for v in kill["violations"]]
    converged = sum(1 for r in results if r["converged"])
    primitives: dict = {}
    for r in results:
        for kind in r["events"]:
            primitives[kind] = primitives.get(kind, 0) + 1
    passes = bool(
        not violations
        and converged == len(results)
        and kill["requests_lost"] == 0
        and kill["requests_redone"] > 0
        and kill["duplicates_replayed_from_journal"]
        == kill["duplicates_resubmitted"]
        and kill["restart_to_first_token_s"] is not None
    )
    return {
        "benchmark": "serve_chaos",
        "metric": "gateway_restart_to_first_token",
        "unit": ("s (SIGKILL mid-dispatch -> journal recover -> first "
                 "token; plus N seeded supervisor+gateway campaigns "
                 "with zero request-plane invariant violations)"),
        "value": kill["restart_to_first_token_s"],
        "campaigns": {
            "campaigns": len(results),
            "converged": converged,
            "violation_count": len(violations),
            "violations": violations[:50],
            "primitives": dict(sorted(primitives.items())),
            "accepted": sum(r["accepted"] for r in results),
            "completed": sum(r["completed"] for r in results),
            "expired": sum(r["expired"] for r in results),
            "sheds": sum(r["sheds"] for r in results),
            "requeues": sum(r["requeues"] for r in results),
            "gateway_kills": sum(r["gateway_kills"] for r in results),
            "redone_after_kill": sum(r["redone_after_kill"]
                                     for r in results),
        },
        "kill_drill": kill,
        "passes": passes,
    }


# ------------------------------------------------- gateway fleet (sharding)


# The replica-kill MTTR budget the --fleet gate enforces: a dead
# replica is reaped at the next fleet tick, and the partition
# reassignment + journal adoption happen INSIDE that tick — so anything
# past two tick intervals (FleetPolicy.tick_every_s = 2 s) means the
# reap path regressed, not that the fleet was busy.
FLEET_MTTR_BUDGET_S = 4.0

# The front-door serialization model for the N=1 vs N=4 scaling pair:
# each replica admits one request per admit_cost_s (the fsync'd-journal
# admission ceiling, ~20 accepts/sec/door) and refuses 429-overload
# past a 1 s backlog. The trace offers ~3x one door's ceiling in TINY
# requests, so the decode plane never bottlenecks — the REQUEST plane
# is what the fleet shards, and what this pair isolates.
FLEET_ADMIT_COST_S = 0.05
FLEET_SCALING_TRAFFIC = dict(duration_s=60.0, base_rps=60.0, seed=31)


def _pctile(sorted_values: list, q: float):
    """Nearest-rank percentile over an ascending list (the gateway
    report's convention); None on empty."""
    if not sorted_values:
        return None
    idx = min(len(sorted_values) - 1,
              max(0, int(round(q * (len(sorted_values) - 1)))))
    return sorted_values[idx]


def _fleet_drive_policy(deadline_s: float):
    from tritonk8ssupervisor_tpu.serving import gateway as gw_mod

    return gw_mod.GatewayPolicy(
        max_seq_len=512, slots_per_slice=4, prefill_chunk=64,
        queue_budget=64, bucket_bounds=(64, 128, 256),
        poll_every_s=2.0, default_deadline_s=deadline_s,
    )


def _fleet_drive_engines(num_slices: int, gw_policy) -> dict:
    from tritonk8ssupervisor_tpu.serving import gateway as gw_mod

    cost = gw_mod.DecodeCostModel()
    return {
        i: gw_mod.ModeledEngine(slots=gw_policy.slots_per_slice,
                                prefill_chunk=gw_policy.prefill_chunk,
                                cost=cost)
        for i in range(num_slices)
    }


def _run_fleet_scaling_drive(workdir: Path, replicas: int,
                             num_slices: int = 8) -> dict:
    """One arm of the N=1 vs N=4 accepted-throughput pair: the SAME
    saturating keyed trace (FLEET_SCALING_TRAFFIC) against a fleet of
    `replicas` admission doors over the same decode pool. Tiny
    requests + ample slots keep decode out of the way; the modeled
    admission cost (FLEET_ADMIT_COST_S) makes the front door the
    bottleneck N=1 suffers and N=4 shards away. Fully deterministic;
    the merged-journal fold is the accepted count and the fleet
    invariant checker runs on every arm."""
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision.state import RunPaths
    from tritonk8ssupervisor_tpu.serving import fleet as fleet_mod
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod
    from tritonk8ssupervisor_tpu.serving import traffic as traffic_mod
    from tritonk8ssupervisor_tpu.testing.chaos import ServeInvariantChecker

    root = Path(workdir)
    root.mkdir(parents=True, exist_ok=True)
    clock = SimClock()
    paths = RunPaths(root)
    ledger = events_mod.EventLedger(paths.events, clock=clock.time,
                                    echo=lambda line: None, fsync=False)
    gw_policy = _fleet_drive_policy(60.0)
    fleet = fleet_mod.GatewayFleet(
        _fleet_drive_engines(num_slices, gw_policy), paths, ledger,
        policy=fleet_mod.FleetPolicy(replicas=replicas,
                                     admit_cost_s=FLEET_ADMIT_COST_S),
        gateway_policy=gw_policy, clock=clock.time, fsync=False,
    )
    duration_s = float(FLEET_SCALING_TRAFFIC["duration_s"])
    model = traffic_mod.TrafficModel(
        base_rps=float(FLEET_SCALING_TRAFFIC["base_rps"]),
        diurnal_amplitude=0.0,
        seed=int(FLEET_SCALING_TRAFFIC["seed"]),
        prompt_lens=(8, 16), new_tokens_choices=(4, 8),
        deadline_s=60.0, key_prefix="scale",
    )
    arrivals = traffic_mod.generate_arrivals(model, duration_s)
    clock.launch()
    clock.begin()
    try:
        report = fleet_mod.drive_fleet(fleet, arrivals, clock,
                                       duration_s)
    finally:
        clock.release()
    journals = [fleet.reqlogs[rid].replay() for rid in fleet.replica_ids]
    view = reqlog_mod.fold(reqlog_mod.merge_records(*journals))
    accepted = sum(1 for kv in view.keys.values() if kv.accepts > 0)
    checker = ServeInvariantChecker(gw_policy)
    violations = checker.check_fleet(journals, ledger.replay())
    if not report["quiescent"]:
        violations.append(
            f"scaling drive (N={replicas}) not quiescent at drive end"
        )
    return {
        "replicas": replicas,
        "num_slices": num_slices,
        "duration_s": duration_s,
        "offered": report["offered"],
        "accepted": accepted,
        "accepted_per_sec": round(accepted / duration_s, 2),
        "completed": sum(kv.completions for kv in view.keys.values()),
        "expired": sum(kv.expiries for kv in view.keys.values()),
        "frontdoor_sheds": fleet.frontdoor_sheds,
        "p50_latency_s": report["p50_latency_s"],
        "p99_latency_s": report["p99_latency_s"],
        "violations": violations,
        "converged": report["quiescent"],
    }


def _run_fleet_streaming_drive(workdir: Path, replicas: int = 4,
                               num_slices: int = 6,
                               duration_s: float = 120.0,
                               base_rps: float = 4.0) -> dict:
    """The streaming-TTFT datapoint: one N-replica drive where EVERY
    request streams (`stream=True` + an `on_token` sink counting
    chunks), a seeded share of the traffic multi-turn sessions pinned
    to their replica. The comparison needs no second drive: for a
    non-streaming client the first byte IS the full response, so the
    full-response latency distribution over the SAME arrivals is the
    non-streaming TTFT — streaming p99 TTFT must sit strictly below
    it."""
    from tritonk8ssupervisor_tpu.provision import events as events_mod
    from tritonk8ssupervisor_tpu.provision.state import RunPaths
    from tritonk8ssupervisor_tpu.serving import fleet as fleet_mod
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod
    from tritonk8ssupervisor_tpu.serving import traffic as traffic_mod
    from tritonk8ssupervisor_tpu.testing.chaos import ServeInvariantChecker

    root = Path(workdir)
    root.mkdir(parents=True, exist_ok=True)
    clock = SimClock()
    paths = RunPaths(root)
    ledger = events_mod.EventLedger(paths.events, clock=clock.time,
                                    echo=lambda line: None, fsync=False)
    gw_policy = _fleet_drive_policy(90.0)
    fleet = fleet_mod.GatewayFleet(
        _fleet_drive_engines(num_slices, gw_policy), paths, ledger,
        policy=fleet_mod.FleetPolicy(replicas=replicas),
        gateway_policy=gw_policy, clock=clock.time, fsync=False,
    )
    model = traffic_mod.TrafficModel(
        base_rps=base_rps, diurnal_amplitude=0.2,
        diurnal_period_s=600.0, seed=47, deadline_s=90.0,
        key_prefix="stream", session_share=0.3, session_turns=3,
        session_think_s=5.0,
    )
    arrivals = traffic_mod.generate_arrivals(model, duration_s)
    sink = {"chunks": 0, "tokens": 0}

    def on_token(request, n_new, ids, now) -> None:
        # the delivery sink: chunks flow as decode steps land, not at
        # done_at — `ids` is None on modeled engines (token counts,
        # not token values, are what the model tracks)
        sink["chunks"] += 1
        sink["tokens"] += int(n_new)

    for req in arrivals:
        req.stream = True
        req.on_token = on_token
    clock.launch()
    clock.begin()
    try:
        report = fleet_mod.drive_fleet(fleet, arrivals, clock,
                                       duration_s)
    finally:
        clock.release()
    done = [r for rid in fleet.replica_ids
            for r in fleet.gateways[rid].metrics.completed]
    ttfts = sorted(r.first_token_at - r.arrival for r in done
                   if r.first_token_at is not None)
    fulls = sorted(r.done_at - r.arrival for r in done
                   if r.done_at is not None)
    journals = [fleet.reqlogs[rid].replay() for rid in fleet.replica_ids]
    checker = ServeInvariantChecker(gw_policy)
    violations = checker.check_fleet(journals, ledger.replay())
    if not report["quiescent"]:
        violations.append("streaming drive not quiescent at drive end")
    if len(ttfts) != len(done):
        violations.append(
            f"streaming: {len(done) - len(ttfts)} completed request(s) "
            "never recorded a first token"
        )
    sessions = {r.session_id for r in arrivals
                if r.session_id is not None}
    return {
        "replicas": replicas,
        "num_slices": num_slices,
        "duration_s": duration_s,
        "offered": report["offered"],
        "completed": len(done),
        "streamed_chunks": sink["chunks"],
        "streamed_tokens": sink["tokens"],
        "sessions": len(sessions),
        "session_turns_offered": sum(1 for r in arrivals
                                     if r.session_id is not None),
        "ttft_p50_s": _pctile(ttfts, 0.50),
        "ttft_p99_s": _pctile(ttfts, 0.99),
        "full_response_p50_s": _pctile(fulls, 0.50),
        "full_response_p99_s": _pctile(fulls, 0.99),
        "violations": violations,
        "converged": report["quiescent"],
    }


def run_fleet_benchmark(campaigns: int = 25) -> dict:
    """The federated-gateway acceptance datapoint (BENCH_fleet.json):

    - the N=1 vs N=4 scaling pair: the same saturating keyed trace
      against one admission door vs four — accepted throughput must
      scale >= 2.5x (the front door is the modeled bottleneck; decode
      never is);
    - the streaming-TTFT drive: every request streams; p99 first-token
      must sit strictly below the non-streaming client's p99 first
      byte (= full-response latency over the same arrivals);
    - the replica-kill drill (testing/chaos.run_fleet_kill_drill):
      partitions reassigned, ZERO accepted requests lost across the
      merged N-shard fold, duplicates of the dead replica's completions
      answered by the successor, MTTR within the tick budget;
    - N seeded fleet chaos campaigns (replica-kill / revive / forced
      lease-expiry), every one folded through
      ServeInvariantChecker.check_fleet — merged conservation, no
      double service, partition exclusivity, lease-epoch exclusivity,
      no cross-lease dispatch. Zero violations is the bar.
    """
    from tritonk8ssupervisor_tpu.testing import chaos

    results: list = []
    violations: list = []
    with tempfile.TemporaryDirectory(prefix="tk8s-fleet-") as tmp:
        for seed in range(1, campaigns + 1):
            out = chaos.run_fleet_campaign(
                chaos.generate_fleet_scenario(seed),
                Path(tmp) / f"seed-{seed}",
            )
            results.append(out)
            violations += [f"seed {seed}: {v}"
                           for v in out["violations"]]
        kill = chaos.run_fleet_kill_drill(Path(tmp) / "kill-drill")
        n1 = _run_fleet_scaling_drive(Path(tmp) / "scale-n1", 1)
        n4 = _run_fleet_scaling_drive(Path(tmp) / "scale-n4", 4)
        streaming = _run_fleet_streaming_drive(Path(tmp) / "streaming")
    violations += [f"kill-drill: {v}" for v in kill["violations"]]
    violations += [f"scaling-n1: {v}" for v in n1["violations"]]
    violations += [f"scaling-n4: {v}" for v in n4["violations"]]
    violations += [f"streaming: {v}" for v in streaming["violations"]]
    converged = sum(1 for r in results if r["converged"])
    primitives: dict = {}
    for r in results:
        for kind in r["events"]:
            primitives[kind] = primitives.get(kind, 0) + 1
    ratio = (round(n4["accepted_per_sec"] / n1["accepted_per_sec"], 2)
             if n1["accepted_per_sec"] else None)
    streams_faster = (
        streaming["ttft_p99_s"] is not None
        and streaming["full_response_p99_s"] is not None
        and streaming["ttft_p99_s"] < streaming["full_response_p99_s"]
    )
    passes = bool(
        not violations
        and converged == len(results)
        and ratio is not None and ratio >= 2.5
        and streams_faster
        and kill["requests_lost"] == 0
        and kill["partitions_reassigned"] > 0
        and kill["duplicates_replayed_from_journal"]
        == kill["duplicates_resubmitted"]
        and kill["kill_to_reassign_s"] is not None
        and kill["kill_to_reassign_s"] <= FLEET_MTTR_BUDGET_S
    )
    return {
        "benchmark": "gateway_fleet",
        "metric": "n4_over_n1_accepted_throughput",
        "unit": ("x (same saturating keyed trace, one admission door "
                 "vs four sharding the key space; >= 2.5x plus "
                 "streaming p99 TTFT strictly under the non-streaming "
                 "p99 first byte, a lossless replica-kill drill, and "
                 "zero fleet-invariant violations is the acceptance "
                 "bar)"),
        "value": ratio,
        "scaling": {
            "n1": n1,
            "n4": n4,
            "ratio": ratio,
            "admit_cost_s": FLEET_ADMIT_COST_S,
        },
        "streaming": streaming,
        "campaigns": {
            "campaigns": len(results),
            "converged": converged,
            "violation_count": len(violations),
            "violations": violations[:50],
            "primitives": dict(sorted(primitives.items())),
            "offered": sum(r["offered"] for r in results),
            "accepted": sum(r["accepted"] for r in results),
            "completed": sum(r["completed"] for r in results),
            "expired": sum(r["expired"] for r in results),
            "requeues": sum(r["requeues"] for r in results),
            "replica_kills": sum(r["replica_kills"] for r in results),
            "reassignments": sum(r["reassignments"] for r in results),
            "lease_grants": sum(r["lease_grants"] for r in results),
            "lease_expiries": sum(r["lease_expiries"]
                                  for r in results),
            "lease_revokes": sum(r["lease_revokes"] for r in results),
            "lease_fenced_pulls": sum(r["lease_fenced_pulls"]
                                      for r in results),
        },
        "kill_drill": kill,
        "mttr_budget_s": FLEET_MTTR_BUDGET_S,
        "passes": passes,
    }


# ------------------------------------------------- autoscale (elasticity)


AUTOSCALE_TRAFFIC = dict(
    # the diurnal+burst trace (serving/traffic.py): peaks that need the
    # whole 4-slice fleet, troughs that need one slice, and a 3x burst
    # landing IN the trough — the moment elasticity is hardest
    duration_s=2400.0, base_rps=4.0, diurnal_amplitude=0.7,
    diurnal_period_s=1200.0, bursts=((900.0, 180.0, 3.0),), seed=11,
)

# The unattended scale-up MTTR budget the gate enforces, derived from
# the campaign policy the way the supervise drill derives its heal
# budget: the burst may land mid-drain (<= 1 interval to abort it), the
# abort arms the 60 s cooldown, confirmation needs 2 fresh windows
# (2 x 30 s), one tick acts, and the warm provision is ~30 s — ~240 s
# worst case, with slack for signal propagation. The MEASURED value in
# BENCH_autoscale.json is the evidence; the gate compares against
# max(committed, budget) because the co-actor interleaving at equal
# virtual instants makes the measurement noisy run to run, and a
# budget-anchored gate catches real regressions (a cooldown bug, a
# stuck drain) without flaking on scheduler noise.
AUTOSCALE_MTTR_BUDGET_S = 300.0


def run_autoscale_cost_drives(workdir: Path,
                              duration_s: float | None = None
                              ) -> tuple[dict, dict]:
    """The elastic-vs-static A/B: the SAME diurnal+burst stream served
    by the closed loop (supervisor autoscaling on the gateway's demand
    signal) and by a static 4-slice fleet. Returns (elastic, static)
    drive results — cost-per-served-token is the honest comparison.

    `duration_s` trims the drive for the --check gate: the seeded
    arrival stream is prefix-identical (open-loop: arrivals are a pure
    function of the model), so a 1500 s drive reproduces the full
    bench's behavior through the trough, the burst, and the scale-up —
    the MTTR stays comparable to the committed 2400 s run — at a
    fraction of the wall cost."""
    from tritonk8ssupervisor_tpu.testing import chaos

    traffic = dict(AUTOSCALE_TRAFFIC)
    if duration_s is not None:
        traffic["duration_s"] = float(duration_s)
    policy = chaos.default_autoscale_policy(4)
    elastic = chaos.run_autoscale_drive(
        Path(workdir) / "elastic", autoscale_policy=policy, **traffic,
    )
    static = chaos.run_autoscale_drive(
        Path(workdir) / "static", autoscale_policy=None, **traffic,
    )
    return elastic, static


def run_autoscale_benchmark(campaigns: int = 25) -> dict:
    """The SLO-driven-autoscaling acceptance datapoint
    (BENCH_autoscale.json):

    - **cost**: the diurnal+burst trace served elastic vs static —
      cost-per-served-token (active-slice-hours / 1k completed tokens)
      must BEAT the static fleet while p99 stays inside the SLO;
    - **scale-up MTTR**: burst onset -> SCALE_DONE(up) on the ledger,
      unattended;
    - **the three named drills**: gateway SIGKILL mid-drain (journal
      resumes the work, the drain still settles), provisioning failure
      mid-scale-up (SCALE_ABORT -> cooldown -> retried, never
      double-provisioned), supervisor SIGKILL mid-scale (restart
      resumes the open SCALE_START from the ledger);
    - **N seeded elasticity campaigns** (testing/chaos.py
      `generate_autoscale_scenario`): every one folded through the
      ServeInvariantChecker with the scale invariants armed — request
      conservation across every scale-down, zero dispatches to
      DRAINING slices, desired-count changes only on confirmed fresh
      windows, no action while the thrash breaker holds, strictly
      serialised scales. Zero violations is the bar.
    """
    from tritonk8ssupervisor_tpu.testing import chaos

    policy = chaos.default_autoscale_policy(4)
    results: list = []
    violations: list = []
    with tempfile.TemporaryDirectory(prefix="tk8s-autoscale-") as tmp:
        root = Path(tmp)
        elastic, static = run_autoscale_cost_drives(root)
        gw_kill = chaos.run_autoscale_drive(
            root / "gw-kill", autoscale_policy=policy,
            kill_gateway_on_drain=True, **AUTOSCALE_TRAFFIC,
        )
        up_loss = chaos.run_autoscale_drive(
            root / "up-loss", autoscale_policy=policy,
            fail_applies=1, **AUTOSCALE_TRAFFIC,
        )
        sup_kill = chaos.run_autoscale_drive(
            root / "sup-kill", autoscale_policy=policy,
            supervisor_kill_on="destroy", **AUTOSCALE_TRAFFIC,
        )
        for seed in range(1, campaigns + 1):
            scenario = chaos.generate_autoscale_scenario(seed)
            out = chaos.run_autoscale_campaign(scenario,
                                               root / f"seed-{seed}")
            results.append(out)
            violations += [f"seed {seed}: {v}"
                           for v in out["violations"]]
    for label, drill in (("elastic", elastic), ("static", static),
                         ("gw-kill", gw_kill), ("up-loss", up_loss),
                         ("sup-kill", sup_kill)):
        violations += [f"{label}: {v}" for v in drill["violations"]]
    converged = sum(1 for r in results if r["converged"])
    primitives: dict = {}
    for r in results:
        for kind in r["events"]:
            primitives[kind] = primitives.get(kind, 0) + 1
    cost_elastic = elastic["slice_hours_per_1k_tokens"]
    cost_static = static["slice_hours_per_1k_tokens"]
    savings = (round(1.0 - cost_elastic / cost_static, 4)
               if cost_elastic and cost_static else None)
    passes = bool(
        not violations
        and converged == len(results)
        and cost_elastic is not None and cost_static is not None
        and cost_elastic < cost_static
        and elastic["p99_latency_s"] is not None
        and elastic["p99_latency_s"] <= policy.slo_p99_s
        and elastic["scale_up_mttr_s"] is not None
        and elastic["scale_up_mttr_s"] <= AUTOSCALE_MTTR_BUDGET_S
        and elastic["scales"]["done_down"] > 0
        and elastic["scales"]["done_up"] > 0
        and gw_kill["gateway_kills"] == 1
        and gw_kill["redone_after_kill"] > 0
        and gw_kill["converged"]
        and up_loss["scales"]["aborted"] >= 1
        and up_loss["scales"]["done_up"] >= 1
        and sup_kill["supervisor_restarts"] >= 1
        and sup_kill["converged"]
    )
    return {
        "benchmark": "autoscale",
        "metric": "scale_up_mttr_s",
        "unit": ("s (burst onset -> SCALE_DONE up, unattended; plus "
                 "cost-per-served-token elastic vs static under the "
                 "diurnal+burst trace, three crash drills, and N "
                 "seeded elasticity campaigns with zero scale-"
                 "invariant violations)"),
        "value": elastic["scale_up_mttr_s"],
        "mttr_budget_s": AUTOSCALE_MTTR_BUDGET_S,
        "slo_p99_s": policy.slo_p99_s,
        "cost_savings_vs_static": savings,
        "elastic": elastic,
        "static": static,
        "drills": {
            "gateway_kill_mid_drain": gw_kill,
            "slice_loss_mid_scale_up": up_loss,
            "supervisor_kill_mid_scale": sup_kill,
        },
        "campaigns": {
            "campaigns": len(results),
            "converged": converged,
            "violation_count": len(violations),
            "violations": violations[:50],
            "primitives": dict(sorted(primitives.items())),
            "accepted": sum(r["accepted"] for r in results),
            "completed": sum(r["completed"] for r in results),
            "expired": sum(r["expired"] for r in results),
            "sheds": sum(r["sheds"] for r in results),
            "scales_done": sum(r["scales"]["done_up"]
                               + r["scales"]["done_down"]
                               for r in results),
            "scales_aborted": sum(r["scales"]["aborted"]
                                  for r in results),
            "gateway_kills": sum(r["gateway_kills"] for r in results),
            "supervisor_restarts": sum(r["supervisor_restarts"]
                                       for r in results),
        },
        "passes": passes,
    }


# ------------------------------------------ co-scheduling (one fleet)


COSCHEDULE_TRAFFIC = dict(
    # three diurnal periods STARTING IN THE TROUGH (phase 0.75 — the
    # run opens with training holding the fleet), peaks that need ~2
    # serving slices, troughs that need one, and a 2.2x burst riding
    # the FIRST PEAK — the moment a static half-fleet drowns and the
    # co-scheduled fleet must preempt training to marshal everything
    duration_s=3600.0, base_rps=1.5, diurnal_amplitude=0.85,
    diurnal_phase=0.75, diurnal_period_s=1200.0,
    bursts=((600.0, 270.0, 2.2),), seed=13,
)

# The unattended preemption-MTTR budget (burst onset -> ROLE_CHANGED
# to serving on the ledger), derived from the campaign policy:
# pressure builds within ~1 tick, confirmation needs 2 fresh windows
# (2 x 30 s), the PREEMPT_NOTICE opens the checkpoint window, the
# trainer acks within one poll interval (5 s), the ack folds on the
# next tick (30 s), and the role flips the same tick — ~150 s worst
# case, with slack for a hand-back abort first. Same budget-anchored
# gating rationale as AUTOSCALE_MTTR_BUDGET_S.
COSCHEDULE_MTTR_BUDGET_S = 300.0

# The training side of the static comparison: two slices stepping at
# the VirtualTrainer's rate for the whole run, no preemptions, no
# resumes — what a dedicated half-fleet banks.
COSCHEDULE_TRAINER_RATE = 0.5  # steps per slice-second
COSCHEDULE_CHECKPOINT_EVERY = 60  # steps per durable checkpoint


def run_coschedule_cost_drives(workdir: Path,
                               duration_s: float | None = None
                               ) -> tuple[dict, dict, float]:
    """The one-fleet-vs-two-half-fleets A/B: the SAME diurnal+burst
    stream served by a co-scheduled 4-slice fleet (the allocator hands
    troughs to training and preempts on the surge) and by a static
    2-slice serving half, next to a static 2-slice training half that
    banks `rate * 2 * duration` steps uninterrupted. Returns
    (coscheduled, static_serve, static_train_steps) — the co-scheduled
    fleet must beat the halves on BOTH goodput and training steps."""
    from tritonk8ssupervisor_tpu.testing import chaos

    traffic = dict(COSCHEDULE_TRAFFIC)
    if duration_s is not None:
        traffic["duration_s"] = float(duration_s)
    cosched = chaos.run_coschedule_drive(
        Path(workdir) / "cosched", num_slices=4,
        alloc_policy=chaos.default_alloc_policy(4),
        trainer_rate=COSCHEDULE_TRAINER_RATE,
        checkpoint_every=COSCHEDULE_CHECKPOINT_EVERY, **traffic,
    )
    static_serve = chaos.run_coschedule_drive(
        Path(workdir) / "static-serve", num_slices=2,
        alloc_policy=None, **traffic,
    )
    static_train_steps = (COSCHEDULE_TRAINER_RATE * 2
                          * traffic["duration_s"])
    return cosched, static_serve, static_train_steps


def run_allocator_benchmark(campaigns: int = 25) -> dict:
    """The train/serve co-scheduling acceptance datapoint
    (BENCH_allocator.json):

    - **one fleet vs two half-fleets**: the diurnal+burst trace served
      co-scheduled (4 elastic slices) vs split static (2 serve + 2
      train) — the ONE fleet must complete MORE requests AND bank MORE
      training steps (steps/day is the same comparison scaled);
    - **preemption MTTR**: burst onset -> ROLE_CHANGED(serving) on the
      ledger, unattended, within the policy-derived budget;
    - **preemption cost**: every trainer resume loses <= one checkpoint
      interval of steps (the drain-notice flush makes the acked path
      ~0; the periodic checkpoint bounds the forced path);
    - **the three named drills**: supervisor SIGKILL between
      PREEMPT_NOTICE and ROLE_CHANGED (restart resumes the SAME
      handover id — the serialised-handover invariant would name a
      sibling), a trainer that never acks (bounded wait -> FORCED
      preemption, loss still bounded), and a tenant flood against the
      WFQ admission queue (the flooding tenant is clamped near its
      weight share; the base tenants keep completing);
    - **N seeded co-scheduling campaigns** (testing/chaos.py
      `generate_coschedule_scenario`): every one folded through the
      ServeInvariantChecker with the allocation invariants armed —
      role exclusivity, handover protocol (ack before role change,
      forced only past the deadline), confirmed fresh windows, zero
      dispatches to TRAINING slices, request conservation throughout.
      Zero violations is the bar.
    """
    from tritonk8ssupervisor_tpu.testing import chaos

    policy = chaos.default_alloc_policy(4)
    results: list = []
    violations: list = []
    with tempfile.TemporaryDirectory(prefix="tk8s-alloc-") as tmp:
        root = Path(tmp)
        cosched, static_serve, static_train_steps = \
            run_coschedule_cost_drives(root)
        kill = chaos.run_coschedule_drive(
            root / "kill-mid-handover", num_slices=4,
            alloc_policy=policy, kill_on_notice=1,
            trainer_rate=COSCHEDULE_TRAINER_RATE,
            checkpoint_every=COSCHEDULE_CHECKPOINT_EVERY,
            **COSCHEDULE_TRAFFIC,
        )
        noack = chaos.run_coschedule_drive(
            root / "never-ack", num_slices=4,
            alloc_policy=policy, trainer_ack=False,
            trainer_rate=COSCHEDULE_TRAINER_RATE,
            checkpoint_every=COSCHEDULE_CHECKPOINT_EVERY,
            **COSCHEDULE_TRAFFIC,
        )
        flood = chaos.run_coschedule_drive(
            root / "tenant-flood", num_slices=4,
            alloc_policy=policy,
            tenants={"base": 3.0, "flood": 1.0},
            flood={"tenant": "flood", "at": 500.0,
                   "duration": 180.0, "rps": 6.0},
            trainer_rate=COSCHEDULE_TRAINER_RATE,
            checkpoint_every=COSCHEDULE_CHECKPOINT_EVERY,
            **COSCHEDULE_TRAFFIC,
        )
        for seed in range(1, campaigns + 1):
            scenario = chaos.generate_coschedule_scenario(seed)
            out = chaos.run_coschedule_campaign(scenario,
                                                root / f"seed-{seed}")
            results.append(out)
            violations += [f"seed {seed}: {v}"
                           for v in out["violations"]]
    for label, drill in (("cosched", cosched),
                         ("static-serve", static_serve),
                         ("kill-mid-handover", kill),
                         ("never-ack", noack),
                         ("tenant-flood", flood)):
        violations += [f"{label}: {v}" for v in drill["violations"]]
    converged = sum(1 for r in results if r["converged"])
    primitives: dict = {}
    for r in results:
        for kind in r["events"]:
            primitives[kind] = primitives.get(kind, 0) + 1
    day = 86400.0
    duration = COSCHEDULE_TRAFFIC["duration_s"]
    cosched_steps = cosched["training"]["steps"]
    max_resume_loss = max(
        (r["steps_lost"] for r in cosched["training"]["resumes"]),
        default=0,
    )
    passes = bool(
        not violations
        and converged == len(results)
        and cosched["completed"] > static_serve["completed"]
        and cosched_steps > static_train_steps
        and cosched["preempt_mttr_s"] is not None
        and cosched["preempt_mttr_s"] <= COSCHEDULE_MTTR_BUDGET_S
        and max_resume_loss <= COSCHEDULE_CHECKPOINT_EVERY
        and cosched["handovers"]["preemptions"] > 0
        and cosched["handovers"]["handbacks"] > 0
        and kill["supervisor_restarts"] >= 1 and kill["converged"]
        and noack["handovers"]["forced"] >= 1 and noack["converged"]
        and flood["converged"]
    )
    return {
        "benchmark": "allocator",
        "metric": "preempt_mttr_s",
        "unit": ("s (burst onset -> ROLE_CHANGED to serving, "
                 "unattended; plus goodput + training steps on ONE "
                 "co-scheduled fleet vs two static half-fleets under "
                 "the diurnal+burst trace, three crash/fairness "
                 "drills, and N seeded co-scheduling campaigns with "
                 "zero allocation-invariant violations)"),
        "value": cosched["preempt_mttr_s"],
        "mttr_budget_s": COSCHEDULE_MTTR_BUDGET_S,
        "checkpoint_every_steps": COSCHEDULE_CHECKPOINT_EVERY,
        "max_resume_steps_lost": max_resume_loss,
        "goodput": {
            "coscheduled_completed": cosched["completed"],
            "static_serve_completed": static_serve["completed"],
            "margin": cosched["completed"] - static_serve["completed"],
        },
        "training": {
            "coscheduled_steps": cosched_steps,
            "static_train_steps": static_train_steps,
            "coscheduled_steps_per_day": round(
                cosched_steps / duration * day, 1),
            "static_steps_per_day": round(
                static_train_steps / duration * day, 1),
            "steps_lost": cosched["training"]["steps_lost"],
            "resumes": len(cosched["training"]["resumes"]),
        },
        "coscheduled": cosched,
        "static_serve": static_serve,
        "static_train_steps": static_train_steps,
        "drills": {
            "supervisor_kill_mid_handover": kill,
            "never_acking_trainer": noack,
            "tenant_flood": flood,
        },
        "campaigns": {
            "campaigns": len(results),
            "converged": converged,
            "violation_count": len(violations),
            "violations": violations[:50],
            "primitives": dict(sorted(primitives.items())),
            "accepted": sum(r["accepted"] for r in results),
            "completed": sum(r["completed"] for r in results),
            "expired": sum(r["expired"] for r in results),
            "sheds": sum(r["sheds"] for r in results),
            "handovers": sum(r["handovers"]["notices"]
                             for r in results),
            "preemptions": sum(r["handovers"]["preemptions"]
                               for r in results),
            "forced": sum(r["handovers"]["forced"] for r in results),
            "training_steps": sum(r["training"]["steps"]
                                  for r in results),
            "training_steps_lost": sum(r["training"]["steps_lost"]
                                       for r in results),
            "supervisor_restarts": sum(r["supervisor_restarts"]
                                       for r in results),
        },
        "passes": passes,
    }


# ----------------------------------------------- telemetry overhead gate


def _obs_telemetry(root: Path, on: bool):
    """A wired Telemetry (spans to `root`, flush mode) or None — the
    two arms of every overhead comparison."""
    if not on:
        return None
    from tritonk8ssupervisor_tpu import obs as obs_lib

    return obs_lib.Telemetry(
        obs_lib.MetricsRegistry(),
        obs_lib.Tracer(
            obs_lib.SpanLog(root / "obs-spans.jsonl",
                            echo=lambda line: None, fsync=False),
            plane=obs_lib.SERVING,
        ),
    )


def _obs_claim_trial(root: Path, on: bool, claims: int) -> float:
    """Wall seconds for `claims` gateway.claim() calls on the
    PRODUCTION claim path: request journal attached (flush mode — the
    fsync cost is per-terminal on the real path, not per claim), no
    fleet view (routes SERVE). Requests are pre-queued so the trial
    times the claim loop, nothing else."""
    from tritonk8ssupervisor_tpu.serving import gateway as gw_mod
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod

    tag = "on" if on else "off"
    reqlog = reqlog_mod.RequestLog(root / f"claim-{tag}.jsonl",
                                   echo=lambda line: None, fsync=False)
    gateway = gw_mod.Gateway(
        {}, None,
        policy=gw_mod.GatewayPolicy(bucket_bounds=(64, 128, 256)),
        reqlog=reqlog, telemetry=_obs_telemetry(root, on),
    )
    queue = gateway.queues[64]
    for i in range(claims):
        req = gw_mod.Request(rid=i, prompt_len=32, max_new_tokens=8,
                             key=f"c{i}", arrival=0.0)
        req.bucket = 64
        queue.append(req)
    t0 = time.perf_counter()
    for i in range(claims):
        gateway.claim(0, 1.0 + i * 1e-6)
    return time.perf_counter() - t0


def _obs_step_trial(root: Path, on: bool, requests: int) -> float:
    """Wall seconds to serve `requests` pre-queued requests through one
    SliceWorker's step loop over a ModeledEngine — the engine-step hot
    path end to end: claims at boundaries, chunked prefill, decode,
    completions (where the spans are emitted). Journal attached, like
    production."""
    from tritonk8ssupervisor_tpu.serving import gateway as gw_mod
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod

    tag = "on" if on else "off"
    reqlog = reqlog_mod.RequestLog(root / f"step-{tag}.jsonl",
                                   echo=lambda line: None, fsync=False)
    engine = gw_mod.ModeledEngine(slots=8, prefill_chunk=64)
    gateway = gw_mod.Gateway(
        {0: engine}, None,
        policy=gw_mod.GatewayPolicy(bucket_bounds=(64, 128, 256),
                                    slots_per_slice=8, prefill_chunk=64),
        reqlog=reqlog, telemetry=_obs_telemetry(root, on),
    )
    queue = gateway.queues[64]
    for i in range(requests):
        req = gw_mod.Request(rid=i, prompt_len=64, max_new_tokens=32,
                             key=f"s{i}", arrival=0.0)
        req.bucket = 64
        queue.append(req)
    worker = gateway.workers[0]
    now = 1.0
    t0 = time.perf_counter()
    while gateway.queue_depth() or worker.inflight:
        dt = worker.step(now)
        now += dt if dt is not None else 0.05
    return time.perf_counter() - t0


def _obs_real_step_trial(root: Path, engine, on: bool,
                         requests: int, vocab: int) -> float:
    """Wall seconds to serve `requests` through one SliceWorker over
    the REAL SlotEngine (serving/engine.py) — the engine step hot path
    the <5% gate names. The ONE engine instance is shared across arms
    (compiled programs are reused; only its tracer is swapped), so the
    arms differ in exactly the instrumentation: per-chunk prefill
    spans, the terminal span batch, and the registry counters'
    histogram observes."""
    import numpy as np

    from tritonk8ssupervisor_tpu import obs as obs_lib
    from tritonk8ssupervisor_tpu.serving import gateway as gw_mod
    from tritonk8ssupervisor_tpu.serving import reqlog as reqlog_mod

    tag = "on" if on else "off"
    engine._tracer = (
        obs_lib.Tracer(
            obs_lib.SpanLog(root / f"real-{tag}-spans.jsonl",
                            echo=lambda line: None, fsync=False),
            plane=obs_lib.SERVING,
        )
        if on else obs_lib.Tracer(None)
    )
    engine.reset()
    reqlog = reqlog_mod.RequestLog(root / f"real-{tag}.jsonl",
                                   echo=lambda line: None, fsync=False)
    gateway = gw_mod.Gateway(
        {0: engine}, None,
        policy=gw_mod.GatewayPolicy(bucket_bounds=(32, 64),
                                    max_seq_len=engine.max_len,
                                    slots_per_slice=engine.slots,
                                    prefill_chunk=engine.prefill_chunk),
        reqlog=reqlog, telemetry=_obs_telemetry(root, on),
    )
    rng = np.random.default_rng(7)
    queue = gateway.queues[32]
    for i in range(requests):
        # decode budget in the traffic model's range (16..96): a
        # request's span set is FIXED-size, so the shorter the decode
        # the more a percentage gate exaggerates it vs production
        req = gw_mod.Request(
            rid=i, prompt_len=24, max_new_tokens=32, key=f"r{i}",
            arrival=0.0,
            tokens=rng.integers(0, vocab, 24).astype(np.int32),
        )
        req.bucket = 32
        queue.append(req)
    worker = gateway.workers[0]
    now = 1.0
    t0 = time.perf_counter()
    while gateway.queue_depth() or worker.inflight:
        dt = worker.step(now)
        now += dt if dt is not None else 0.001
    return time.perf_counter() - t0


def run_obs_overhead_benchmark(trials: int = 7,
                               claims: int = 4000,
                               real_requests: int = 96,
                               real_trials: int = 7,
                               modeled_requests: int = 400,
                               drive_trials: int = 2) -> dict:
    """The instrumentation-overhead acceptance datapoint
    (BENCH_obs.json): the telemetry plane must cost <5% on the engine
    step hot path and on the gateway claim path. Each comparison runs N
    alternating trials per arm and takes the MINIMUM per arm (min-of-N
    strips scheduler noise from a microbenchmark); overhead = on/off-1.

    The GATED arms are the production-shaped ones:

    - **claim**: gateway.claim() with the request journal attached
      (the instrumentation there is one unlabeled counter inc);
    - **real_step**: the REAL SlotEngine (serving/engine.py) under a
      SliceWorker — per-chunk prefill spans, terminal span batches,
      histogram observes, all weighed against actual compiled compute,
      which is what the serve path pays per step.

    The **modeled** arms (ModeledEngine step loop, end-to-end virtual
    clock drive) are recorded as evidence but NOT gated at 5%: a
    modeled step is ~10 microseconds of pure Python — three orders of
    magnitude cheaper than a compiled step — so a percentage against
    it measures the span encoder, not the serving plane. Their honest
    reading is the absolute `per_request_us` they also record.

    The span log runs in flush mode everywhere — on the real serve
    path fsync costs land per TERMINAL settle, amortized over a
    request's whole decode, never per step or per claim."""
    results: dict = {}
    with tempfile.TemporaryDirectory(prefix="tk8s-obs-") as tmp:
        root = Path(tmp)

        def judge(label, iterations, off_times, on_times) -> dict:
            # PAIRED ratios: each (off, on) pair runs back-to-back so
            # machine drift (noisy neighbours, GC) mostly cancels
            # within the pair. The GATED number is the BEST pair — the
            # least-disturbed comparison the box produced; a genuine
            # instrumentation regression raises every pair, so the
            # gate still catches it, while one descheduled trial can't
            # fail a run. The median is reported alongside as the
            # typical-case estimate.
            ratios = sorted(on / off
                            for off, on in zip(off_times, on_times))
            best = ratios[0]
            median = ratios[len(ratios) // 2]
            best_off, best_on = min(off_times), min(on_times)
            entry = {
                "iterations": iterations,
                "trials": len(off_times),
                "off_s": round(best_off, 6),
                "on_s": round(best_on, 6),
                "overhead_pct": round(100.0 * (best - 1.0), 2),
                "overhead_pct_median": round(100.0 * (median - 1.0), 2),
                "per_request_us": round(
                    1e6 * best_off * (best - 1.0)
                    / max(1, iterations), 2),
            }
            results[label] = entry
            return entry

        def compare(label, fn, args, n_trials, iterations) -> dict:
            off_times: list = []
            on_times: list = []
            for _ in range(n_trials):
                off_times.append(fn(root, False, *args))
                on_times.append(fn(root, True, *args))
                for residue in root.glob("*.jsonl"):
                    residue.unlink()
            return judge(label, iterations, off_times, on_times)

        compare("claim", _obs_claim_trial, (claims,), trials, claims)
        compare("modeled_step", _obs_step_trial, (modeled_requests,),
                trials, modeled_requests)

        # the real engine: tiny model, CPU — the two compiled programs
        # are built once (a warm-up run) and shared by both arms
        import jax
        import jax.numpy as jnp

        from tritonk8ssupervisor_tpu.models import TransformerLM
        from tritonk8ssupervisor_tpu.serving import engine as engine_mod

        vocab = 64
        model = TransformerLM(
            vocab_size=vocab, num_layers=1, num_heads=2, embed_dim=32,
            max_seq_len=64, dtype=jnp.float32, logits_dtype=jnp.float32,
        )
        params = model.init(
            jax.random.key(0),
            jax.random.randint(jax.random.key(1), (1, 8), 0, vocab),
            train=False,
        )["params"]
        engine = engine_mod.SlotEngine(
            model, params, slots=4, max_len=64, prefill_chunk=16,
            page_size=16, prefix_cache=False,
        )
        _obs_real_step_trial(root, engine, False, 4, vocab)  # compile
        off_times = []
        on_times = []
        for _ in range(real_trials):
            off_times.append(_obs_real_step_trial(
                root, engine, False, real_requests, vocab))
            on_times.append(_obs_real_step_trial(
                root, engine, True, real_requests, vocab))
        judge("real_step", real_requests, off_times, on_times)
    drive_common = dict(num_slices=4, slots=8, prefill_chunk=64,
                        duration_s=300.0, base_rps=6.0, seed=11,
                        deadline_s=300.0, with_reqlog=True)
    off_times = []
    on_times = []
    offered = 0
    for _ in range(drive_trials):
        off = run_serve_scenario(with_telemetry=False, **drive_common)
        on = run_serve_scenario(with_telemetry=True, **drive_common)
        offered = off["offered_requests"]
        off_times.append(off["drive_wall_s"])
        on_times.append(on["drive_wall_s"])
    best_off, best_on = min(off_times), min(on_times)
    results["modeled_drive"] = {
        "duration_s": drive_common["duration_s"],
        "offered_requests": offered,
        "trials": drive_trials,
        "off_s": round(best_off, 4),
        "on_s": round(best_on, 4),
        "overhead_pct": round(100.0 * (best_on / best_off - 1.0), 2),
        "per_request_us": round(
            1e6 * (best_on - best_off) / max(1, offered), 2),
    }
    gated = max(results["claim"]["overhead_pct"],
                results["real_step"]["overhead_pct"])
    passes = gated < 5.0
    return {
        "benchmark": "obs_overhead",
        "metric": "instrumentation_overhead_pct",
        "unit": ("% (best of N PAIRED wall-clock comparisons, "
                 "telemetry on vs off; the gate covers the gateway "
                 "claim path and the REAL engine step path — <5% is "
                 "the acceptance bar; the modeled arms record absolute "
                 "per-request cost against a Python-only engine three "
                 "orders cheaper than a compiled step)"),
        "value": gated,
        "gated": ["claim", "real_step"],
        **results,
        "passes": passes,
    }


# ------------------------------------------------------ the regression gate


DEFAULT_BASELINE = Path(__file__).resolve().parent / "BENCH_provision.json"
SUPERVISE_BASELINE = Path(__file__).resolve().parent / "BENCH_supervise.json"
ELASTIC_BASELINE = Path(__file__).resolve().parent / "BENCH_elastic.json"
FLEETSCALE_BASELINE = (Path(__file__).resolve().parent
                       / "BENCH_fleetscale.json")
CHAOS_BASELINE = Path(__file__).resolve().parent / "BENCH_chaos.json"
SERVE_BASELINE = Path(__file__).resolve().parent / "BENCH_serve.json"
SERVECHAOS_BASELINE = (Path(__file__).resolve().parent
                       / "BENCH_servechaos.json")
ENGINE_BASELINE = Path(__file__).resolve().parent / "BENCH_engine.json"
OBS_BASELINE = Path(__file__).resolve().parent / "BENCH_obs.json"
AUTOSCALE_BASELINE = (Path(__file__).resolve().parent
                      / "BENCH_autoscale.json")
ALLOCATOR_BASELINE = (Path(__file__).resolve().parent
                      / "BENCH_allocator.json")
FLEET_BASELINE = Path(__file__).resolve().parent / "BENCH_fleet.json"

# run_check's re-simulations are fully deterministic (virtual clocks,
# pinned seeds) and independent of WHICH baseline documents they are
# compared against — so within one process each drive is computed once
# and reused. A suite that exercises the gate twice (passes-against-
# committed, then bites-on-a-bad-baseline) pays for the drives once.
_CHECK_MEMO: dict = {}


def _check_memo(key, fn):
    if key not in _CHECK_MEMO:
        _CHECK_MEMO[key] = fn()
    return _CHECK_MEMO[key]


def run_check(
    baseline: Path = DEFAULT_BASELINE,
    tolerance: float = 0.10,
    supervise_baseline: Path = SUPERVISE_BASELINE,
    elastic_baseline: Path = ELASTIC_BASELINE,
    fleetscale_baseline: Path = FLEETSCALE_BASELINE,
    chaos_baseline: Path = CHAOS_BASELINE,
    serve_baseline: Path = SERVE_BASELINE,
    servechaos_baseline: Path = SERVECHAOS_BASELINE,
    engine_baseline: Path = ENGINE_BASELINE,
    obs_baseline: Path = OBS_BASELINE,
    autoscale_baseline: Path = AUTOSCALE_BASELINE,
    allocator_baseline: Path = ALLOCATOR_BASELINE,
    fleet_baseline: Path = FLEET_BASELINE,
) -> tuple[bool, list[str], dict]:
    """Re-simulate against the committed BENCH_provision.json,
    BENCH_supervise.json, BENCH_elastic.json, and BENCH_fleetscale.json:
    fail when the cold (pipelined DAG) or warm makespan — or the
    supervisor's unattended MTTR, or the elastic drill's
    time-to-training-resumed / steps lost, or the fleet-scale steady
    tick cost / zone-outage heal makespan — regressed more than
    `tolerance`, or when a drill no longer meets its structural budget
    (MTTR beats manual + one interval; steps lost within one checkpoint
    interval; 256-slice tick within 4x the 4-slice tick — superlinear
    tick growth fails here; 32-slice outage healed within 4x one heal).
    The gate that keeps a DAG-edge, cache, reconcile-loop, or
    elastic-resume regression from landing silently. Improvements always
    pass; the committed files are only rewritten by explicit `--out`
    runs."""
    baseline = Path(baseline)
    if not baseline.exists():
        return False, [f"baseline {baseline} missing"], {}
    committed = json.loads(baseline.read_text())
    n_slices = int(committed.get("num_slices", 4))
    # shallow copy: per-call section results attach to `current` below
    current = dict(_check_memo(("provision", n_slices),
                               lambda: run_benchmark(n_slices)))
    problems: list[str] = []

    def compare(label: str, old, new) -> None:
        if old is None or new is None:
            return
        if new > old * (1.0 + tolerance):
            problems.append(
                f"{label} regressed {old:.0f}s -> {new:.0f}s "
                f"(> {tolerance:.0%} over the committed baseline)"
            )

    def compare_floor(label: str, old, new) -> None:
        # for metrics where LOWER is worse (throughput)
        if old is None or new is None:
            return
        if new < old * (1.0 - tolerance):
            problems.append(
                f"{label} regressed {old:.1f} -> {new:.1f} "
                f"(> {tolerance:.0%} under the committed baseline)"
            )

    compare("cold makespan", committed.get("dag", {}).get("wall_s"),
            current["dag"]["wall_s"])
    compare("warm makespan",
            committed.get("warm", {}).get("warm_wall_s"),
            current["warm"]["warm_wall_s"])

    supervise_baseline = Path(supervise_baseline)
    if not supervise_baseline.exists():
        problems.append(f"baseline {supervise_baseline} missing")
    else:
        committed_sup = json.loads(supervise_baseline.read_text())
        n_sup = int(committed_sup.get("num_slices", 4))
        current_sup = _check_memo(
            ("supervise", n_sup),
            lambda: run_supervise_benchmark(n_sup))
        current["supervise"] = current_sup
        compare("unattended MTTR",
                committed_sup.get("unattended_mttr_s",
                                  committed_sup.get("value")),
                current_sup["value"])
        if current_sup["value"] > current_sup["mttr_budget_s"]:
            problems.append(
                f"unattended MTTR {current_sup['value']:.0f}s no longer "
                f"beats the manual-heal budget "
                f"{current_sup['mttr_budget_s']:.0f}s"
            )
        if not current_sup["breaker_drill"]["ends_in_degraded_hold"]:
            problems.append(
                "breaker storm drill no longer ends in degraded-hold"
            )

    elastic_baseline = Path(elastic_baseline)
    if not elastic_baseline.exists():
        problems.append(f"baseline {elastic_baseline} missing (elastic)")
    else:
        committed_el = json.loads(elastic_baseline.read_text())
        n_el = int(committed_el.get("num_slices", 4))
        current_el = _check_memo(
            ("elastic", n_el),
            lambda: run_elastic_benchmark(n_el))
        current["elastic"] = current_el
        compare("elastic time-to-training-resumed",
                committed_el.get("value"), current_el["value"])
        compare("elastic steps lost", committed_el.get("steps_lost"),
                current_el["steps_lost"])
        if not current_el["passes"]:
            problems.append(
                "elastic drill no longer passes (steps lost within one "
                "checkpoint interval, resume within budget, "
                "job-notified/job-resumed on the ledger)"
            )

    fleetscale_baseline = Path(fleetscale_baseline)
    if not fleetscale_baseline.exists():
        problems.append(f"baseline {fleetscale_baseline} missing "
                        "(fleetscale)")
    else:
        committed_fs = json.loads(fleetscale_baseline.read_text())
        current_fs = _check_memo("fleetscale", run_fleetscale_benchmark)
        current["fleetscale"] = current_fs
        big = str(max(int(n) for n in current_fs["ticks"]))
        compare(
            f"{big}-slice steady tick cost",
            committed_fs.get("ticks", {}).get(big, {}).get(
                "steady_tick_cost_s"),
            current_fs["ticks"][big]["steady_tick_cost_s"],
        )
        compare("zone-outage heal makespan",
                committed_fs.get("outage", {}).get("heal_makespan_s"),
                current_fs["outage"]["heal_makespan_s"])
        if not current_fs["passes"]:
            problems.append(
                "fleetscale drill no longer passes (steady tick cost "
                "sublinear in N — 256-slice within 4x the 4-slice tick "
                "and under one reconcile interval; zone outage healed "
                "in parallel within 4x one heal)"
            )

    chaos_baseline = Path(chaos_baseline)
    if not chaos_baseline.exists():
        problems.append(f"baseline {chaos_baseline} missing (chaos)")
    else:
        committed_ch = json.loads(chaos_baseline.read_text())
        n_ch = int(committed_ch.get("campaigns", {}).get("campaigns", 25))
        current_ch = _check_memo(
            ("chaos", n_ch), lambda: run_chaos_benchmark(n_ch))
        current["chaos"] = current_ch
        for violation in (
            current_ch["campaigns"]["violations"]
            + current_ch["blast_radius"]["violations"]
        ):
            problems.append(f"chaos invariant violated: {violation}")
        compare("chaos campaign MTTR (mean)",
                committed_ch.get("value"), current_ch["value"])
        compare("blast-radius MTTR",
                committed_ch.get("blast_radius", {}).get(
                    "blast_radius_mttr_s"),
                current_ch["blast_radius"]["blast_radius_mttr_s"])
        if not current_ch["passes"]:
            problems.append(
                "chaos drill no longer passes (per-domain breaker open "
                "only for the outaged domain, heals flowing in healthy "
                "domains, exactly one canary, zero invariant "
                "violations across all seeded campaigns)"
            )

    serve_baseline = Path(serve_baseline)
    if not serve_baseline.exists():
        problems.append(f"baseline {serve_baseline} missing (serve)")
    else:
        committed_sv = json.loads(serve_baseline.read_text())
        n_sv = int(committed_sv.get("num_slices", 4))
        current_sv = _check_memo(
            ("serve", n_sv), lambda: run_serve_benchmark(n_sv))
        current["serve"] = current_sv
        compare("serve p99 latency",
                committed_sv.get("p99_latency_s"),
                current_sv["p99_latency_s"])
        compare_floor("serve tokens/sec/chip",
                      committed_sv.get("tokens_per_sec_per_chip"),
                      current_sv["tokens_per_sec_per_chip"])
        compare_floor("serve continuous-batching speedup",
                      committed_sv.get("value"), current_sv["value"])
        committed_shared = committed_sv.get("shared_prefix", {})
        current_shared = current_sv.get("shared_prefix", {})
        compare_floor(
            "serve shared-prefix tokens/sec/chip (warm)",
            committed_shared.get("warm", {}).get(
                "tokens_per_sec_per_chip"),
            current_shared.get("warm", {}).get("tokens_per_sec_per_chip"),
        )
        compare_floor("serve prefix-hit speedup (warm over cold)",
                      committed_shared.get(
                          "prefix_speedup_warm_over_cold"),
                      current_shared.get("prefix_speedup_warm_over_cold"))
        compare("serve shared-prefix p99 latency (warm)",
                committed_shared.get("warm", {}).get("p99_latency_s"),
                current_shared.get("warm", {}).get("p99_latency_s"))
        compare_floor(
            "serve paged effective slots (peak busy)",
            committed_sv.get("paged_slots", {}).get("value"),
            current_sv.get("paged_slots", {}).get("value"),
        )
        committed_spec = committed_sv.get("speculative", {})
        current_spec = current_sv.get("speculative", {})
        compare_floor(
            "serve speculative speedup (spec over paged baseline)",
            committed_spec.get("value"), current_spec.get("value"))
        compare("serve speculative p99 latency",
                committed_spec.get("spec", {}).get("p99_latency_s"),
                current_spec.get("spec", {}).get("p99_latency_s"))
        if current_spec.get("acceptance_rate") is None:
            problems.append(
                "serve speculative block lost its acceptance rate "
                "(engines no longer report spec accounting)"
            )
        if not current_sv["passes"]:
            problems.append(
                "serve drill no longer passes (continuous batching >= "
                "2x request-at-a-time at equal or better p99; outage "
                "routed around with bounded p99, in-flight requeued, "
                "sheds only while the breaker/SLO budget demands; "
                "breaker hold admits nothing; shared-prefix warm >= "
                "1.5x the PR-9 per-chip baseline with ~0 shared-prefix "
                "re-prefill on hits; paged slots raise peak busy slots "
                "above the fixed-cache 8 on a memory-equal pool)"
            )

    engine_baseline = Path(engine_baseline)
    if not engine_baseline.exists():
        problems.append(f"baseline {engine_baseline} missing (engine)")
    else:
        # the decode-level A/B runs REAL JAX (benchmarks/decode.py
        # --engine); --check verifies the committed evidence is
        # structurally sound — regenerating it is a hardware-sized
        # measurement, done explicitly, not inside every gate run. The
        # SIM-level prefix/paging throughput regressions gate above.
        committed_en = json.loads(engine_baseline.read_text())
        if not committed_en.get("passes"):
            problems.append(
                "committed BENCH_engine.json does not pass (prefix-warm "
                "A/B must be token-identical with ~0 shared-prefix "
                "re-prefill and a >= 1.05x speedup)"
            )
        if not committed_en.get("token_identical", False):
            problems.append(
                "committed BENCH_engine.json lost token identity "
                "between prefix-cold and prefix-warm drives"
            )
        # the speculative block's structural pins: the committed
        # evidence must show EXACT greedy decoding (token-identical to
        # the drafterless baseline), a recorded acceptance rate, and
        # the >= 1.4x matched-memory speedup the acceptance bar names
        committed_spec_en = committed_en.get("speculative") or {}
        if not committed_spec_en.get("token_identical", False):
            problems.append(
                "committed BENCH_engine.json speculative block is not "
                "token-identical to the drafterless baseline (greedy "
                "speculative decoding must be EXACT)"
            )
        if committed_spec_en.get("acceptance_rate") is None:
            problems.append(
                "committed BENCH_engine.json speculative block lacks "
                "an acceptance rate"
            )
        if (committed_spec_en.get("value") is None
                or committed_spec_en["value"] < 1.4):
            problems.append(
                "committed BENCH_engine.json speculative speedup "
                f"{committed_spec_en.get('value')} is below the 1.4x "
                "matched-memory acceptance bar"
            )

    servechaos_baseline = Path(servechaos_baseline)
    if not servechaos_baseline.exists():
        problems.append(f"baseline {servechaos_baseline} missing "
                        "(serve-chaos)")
    else:
        committed_sc = json.loads(servechaos_baseline.read_text())
        n_sc = int(committed_sc.get("campaigns", {}).get("campaigns", 25))
        current_sc = _check_memo(
            ("serve_chaos", n_sc),
            lambda: run_serve_chaos_benchmark(n_sc))
        current["serve_chaos"] = current_sc
        for violation in current_sc["campaigns"]["violations"]:
            problems.append(
                f"serve-chaos invariant violated: {violation}"
            )
        if current_sc["kill_drill"]["requests_lost"] > 0:
            problems.append(
                "gateway kill drill LOST "
                f"{current_sc['kill_drill']['requests_lost']} accepted "
                "request(s) across the restart (journal recover broken)"
            )
        compare("gateway restart-to-first-token",
                committed_sc.get("value"), current_sc["value"])
        if not current_sc["passes"]:
            problems.append(
                "serve-chaos drill no longer passes (every campaign "
                "converged with zero request-plane violations; kill "
                "drill redoes incomplete work, loses nothing, answers "
                "duplicates from the journal)"
            )

    autoscale_baseline = Path(autoscale_baseline)
    if not autoscale_baseline.exists():
        problems.append(f"baseline {autoscale_baseline} missing "
                        "(autoscale)")
    else:
        # the committed evidence must describe a passing full run (25+
        # campaigns, the three crash drills — regenerating those is an
        # explicit `--autoscale` run); the gate RE-RUNS the elastic-vs-
        # static cost pair, which is where a policy or drain regression
        # would land silently
        committed_as = json.loads(autoscale_baseline.read_text())
        if not committed_as.get("passes"):
            problems.append(
                "committed BENCH_autoscale.json does not pass (cost "
                "under static, p99 within SLO, zero scale-invariant "
                "violations across campaigns + crash drills)"
            )
        if committed_as.get("campaigns", {}).get("violation_count", 1):
            problems.append(
                "committed BENCH_autoscale.json records scale-"
                "invariant violations"
            )
        def _autoscale_pair():
            with tempfile.TemporaryDirectory(
                prefix="tk8s-autoscale-check-"
            ) as tmp:
                return run_autoscale_cost_drives(
                    Path(tmp), duration_s=1500.0
                )

        current_el, current_st = _check_memo("autoscale_cost",
                                             _autoscale_pair)
        current["autoscale"] = {"elastic": current_el,
                                "static": current_st}
        for violation in current_el["violations"] \
                + current_st["violations"]:
            problems.append(f"autoscale invariant violated: {violation}")
        cost_el = current_el["slice_hours_per_1k_tokens"]
        cost_st = current_st["slice_hours_per_1k_tokens"]
        if cost_el is None or cost_st is None or cost_el >= cost_st:
            problems.append(
                f"autoscale cost-per-served-token no longer beats the "
                f"static fleet ({cost_el} vs {cost_st} "
                "slice-hours/1k tokens)"
            )
        slo = committed_as.get("slo_p99_s", 60.0)
        if (current_el["p99_latency_s"] is None
                or current_el["p99_latency_s"] > slo):
            problems.append(
                f"autoscale p99 {current_el['p99_latency_s']}s outside "
                f"the {slo:.0f}s SLO under the diurnal+burst trace"
            )
        if current_el["scale_up_mttr_s"] is None:
            problems.append(
                "autoscale drive recorded no unattended scale-up "
                "under the burst"
            )
        # budget-anchored (see AUTOSCALE_MTTR_BUDGET_S): the committed
        # measurement is noisy run to run, the policy-derived budget is
        # not — the gate fires when MTTR regresses past BOTH
        compare("autoscale scale-up MTTR (vs policy budget)",
                max(committed_as.get("value") or 0.0,
                    AUTOSCALE_MTTR_BUDGET_S),
                current_el["scale_up_mttr_s"])

    allocator_baseline = Path(allocator_baseline)
    if not allocator_baseline.exists():
        problems.append(f"baseline {allocator_baseline} missing "
                        "(allocator)")
    else:
        # committed evidence first (25+ campaigns + the three drills
        # are an explicit `--allocator` run), then RE-RUN the
        # one-fleet-vs-halves pair — where a policy, handover, or WFQ
        # regression would land silently. The pair is deterministic
        # (virtual clock, pinned rng), so "co-scheduled beats both
        # halves" re-verifies exactly.
        committed_al = json.loads(allocator_baseline.read_text())
        if not committed_al.get("passes"):
            problems.append(
                "committed BENCH_allocator.json does not pass (one "
                "fleet beats both static halves, preemption within "
                "budget, zero allocation-invariant violations)"
            )
        if committed_al.get("campaigns", {}).get("violation_count", 1):
            problems.append(
                "committed BENCH_allocator.json records allocation-"
                "invariant violations"
            )
        def _coschedule_triple():
            with tempfile.TemporaryDirectory(
                prefix="tk8s-alloc-check-"
            ) as tmp:
                return run_coschedule_cost_drives(Path(tmp))

        cur_co, cur_st, cur_train = _check_memo("coschedule_cost",
                                                _coschedule_triple)
        current["allocator"] = {"coscheduled": cur_co,
                                "static_serve": cur_st,
                                "static_train_steps": cur_train}
        for violation in cur_co["violations"] + cur_st["violations"]:
            problems.append(f"allocation invariant violated: "
                            f"{violation}")
        if cur_co["completed"] <= cur_st["completed"]:
            problems.append(
                f"co-scheduled goodput no longer beats the static "
                f"serving half-fleet ({cur_co['completed']} vs "
                f"{cur_st['completed']} completed)"
            )
        if cur_co["training"]["steps"] <= cur_train:
            problems.append(
                f"co-scheduled training no longer beats the static "
                f"training half-fleet ({cur_co['training']['steps']} "
                f"vs {cur_train:.0f} steps)"
            )
        max_loss = max(
            (r["steps_lost"] for r in cur_co["training"]["resumes"]),
            default=0,
        )
        if max_loss > COSCHEDULE_CHECKPOINT_EVERY:
            problems.append(
                f"a preemption cost {max_loss} training steps — over "
                f"one checkpoint interval "
                f"({COSCHEDULE_CHECKPOINT_EVERY})"
            )
        if cur_co["preempt_mttr_s"] is None:
            problems.append(
                "co-scheduled drive recorded no unattended preemption "
                "under the burst"
            )
        compare("co-scheduling preemption MTTR (vs policy budget)",
                max(committed_al.get("value") or 0.0,
                    COSCHEDULE_MTTR_BUDGET_S),
                cur_co["preempt_mttr_s"])

    fleet_baseline = Path(fleet_baseline)
    if not fleet_baseline.exists():
        problems.append(f"baseline {fleet_baseline} missing (fleet)")
    else:
        # committed evidence first (the seeded campaign sweep is an
        # explicit `--fleet` run), then RE-RUN the deterministic
        # drives: the N=1 vs N=4 scaling pair, the streaming-TTFT
        # drive, and the replica-kill drill — where a routing, lease,
        # adoption, or streaming regression would land silently
        from tritonk8ssupervisor_tpu.testing import chaos as chaos_mod

        committed_fl = json.loads(fleet_baseline.read_text())
        if not committed_fl.get("passes"):
            problems.append(
                "committed BENCH_fleet.json does not pass (N=4 >= 2.5x "
                "N=1 accepted throughput, streaming p99 TTFT under the "
                "non-streaming p99 first byte, lossless replica-kill "
                "drill, zero fleet-invariant violations)"
            )
        if committed_fl.get("campaigns", {}).get("violation_count", 1):
            problems.append(
                "committed BENCH_fleet.json records fleet-invariant "
                "violations"
            )
        def _fleet_drives():
            with tempfile.TemporaryDirectory(
                prefix="tk8s-fleet-check-"
            ) as tmp:
                return (
                    _run_fleet_scaling_drive(Path(tmp) / "n1", 1),
                    _run_fleet_scaling_drive(Path(tmp) / "n4", 4),
                    _run_fleet_streaming_drive(Path(tmp) / "streaming"),
                    chaos_mod.run_fleet_kill_drill(
                        Path(tmp) / "kill-drill"),
                )

        cur_n1, cur_n4, cur_stream, cur_kill = _check_memo(
            "fleet_drives", _fleet_drives)
        current["fleet"] = {"n1": cur_n1, "n4": cur_n4,
                            "streaming": cur_stream,
                            "kill_drill": cur_kill}
        for violation in (cur_n1["violations"] + cur_n4["violations"]
                          + cur_stream["violations"]
                          + cur_kill["violations"]):
            problems.append(f"fleet invariant violated: {violation}")
        cur_ratio = (cur_n4["accepted_per_sec"]
                     / cur_n1["accepted_per_sec"]
                     if cur_n1["accepted_per_sec"] else None)
        if cur_ratio is None or cur_ratio < 2.5:
            problems.append(
                f"fleet N=4/N=1 accepted-throughput scaling {cur_ratio} "
                "fell under the 2.5x acceptance bar"
            )
        compare_floor("fleet N=4/N=1 accepted-throughput scaling",
                      committed_fl.get("value"), cur_ratio)
        if (cur_stream["ttft_p99_s"] is None
                or cur_stream["full_response_p99_s"] is None
                or cur_stream["ttft_p99_s"]
                >= cur_stream["full_response_p99_s"]):
            problems.append(
                f"fleet streaming p99 TTFT {cur_stream['ttft_p99_s']}s "
                "no longer sits under the non-streaming p99 first byte "
                f"{cur_stream['full_response_p99_s']}s"
            )
        compare("fleet streaming TTFT p99",
                committed_fl.get("streaming", {}).get("ttft_p99_s"),
                cur_stream["ttft_p99_s"])
        if cur_kill["requests_lost"] > 0:
            problems.append(
                f"fleet kill drill LOST {cur_kill['requests_lost']} "
                "accepted request(s) across the replica death "
                "(partition reassignment / journal adoption broken)"
            )
        compare("fleet kill-to-reassign MTTR (vs tick budget)",
                max(committed_fl.get("kill_drill", {}).get(
                    "kill_to_reassign_s") or 0.0,
                    FLEET_MTTR_BUDGET_S),
                cur_kill["kill_to_reassign_s"])

    obs_baseline = Path(obs_baseline)
    if not obs_baseline.exists():
        problems.append(f"baseline {obs_baseline} missing (obs)")
    else:
        committed_obs = json.loads(obs_baseline.read_text())
        if not committed_obs.get("passes"):
            problems.append(
                "committed BENCH_obs.json does not pass (<5% "
                "instrumentation overhead on the claim and real-engine "
                "step paths)"
            )
        current_obs = _check_memo("obs", run_obs_overhead_benchmark)
        current["obs"] = current_obs
        if not current_obs["passes"]:
            problems.append(
                "telemetry overhead gate failed: instrumentation costs "
                f"{current_obs['value']:.1f}% on "
                f"{'/'.join(current_obs['gated'])} (bar: <5%)"
            )
    return not problems, problems, current


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--slices", type=int, default=4)
    parser.add_argument("--resilience", action="store_true",
                        help="run the crash-resume + slice-loss drills "
                        "instead of the schedule comparison")
    parser.add_argument("--warm", action="store_true",
                        help="run only the cold-vs-warm drill (journal + "
                        "cache verified no-op re-provision)")
    parser.add_argument("--supervise", action="store_true",
                        help="run the supervisor drills: unattended MTTR "
                        "for a slice preemption vs the manual-heal "
                        "baseline, plus the breaker storm ending in "
                        "degraded-hold")
    parser.add_argument("--elastic", action="store_true",
                        help="run the elastic-training drill: a real "
                        "supervisor and a real ElasticTrainer as "
                        "virtual-clock co-actors; a t=300s preemption "
                        "costs <= one checkpoint interval of steps and "
                        "training resumes within the detect+confirm+heal "
                        "budget (BENCH_elastic.json)")
    parser.add_argument("--fleetscale", action="store_true",
                        help="run the fleet-scale drills: steady-state "
                        "supervisor tick cost vs N in {4, 64, 256} "
                        "(sublinear via dirty-set reconcile + paged "
                        "listings) and a 32-of-256 zone outage healed "
                        "by parallel slice-scoped heals "
                        "(BENCH_fleetscale.json)")
    parser.add_argument("--chaos", action="store_true",
                        help="run the seeded chaos campaigns: the "
                        "32-of-256 blast-radius drill (per-domain "
                        "breaker, canary re-entry, heals flowing in "
                        "healthy domains) plus N seeded fault "
                        "compositions, every one checked against the "
                        "ledger InvariantChecker (BENCH_chaos.json)")
    parser.add_argument("--campaigns", type=int, default=25,
                        metavar="N", help="--chaos: seeded campaigns to "
                        "run (default 25)")
    parser.add_argument("--serve", action="store_true",
                        help="run the serving-gateway drills: the same "
                        "SimClock open-loop arrival stream (diurnal "
                        "curve + burst storms) served request-at-a-time "
                        "vs continuous-batching, plus a mid-run slice "
                        "outage (route-around, requeue, SLO shedding) "
                        "and a breaker-open hold (BENCH_serve.json)")
    parser.add_argument("--serve-chaos", action="store_true",
                        help="run the request-plane resilience drills: "
                        "N seeded supervisor+gateway campaigns (real "
                        "Supervisor + real Gateway co-simulated on one "
                        "SimClock, request journal + event ledger "
                        "checked for conservation / exactly-once / "
                        "deadline honesty / bounded staleness) plus "
                        "the gateway SIGKILL crash-resume drill "
                        "(BENCH_servechaos.json)")
    parser.add_argument("--autoscale", action="store_true",
                        help="run the SLO-driven autoscaling drills: "
                        "the diurnal+burst trace served elastic vs "
                        "static (cost-per-served-token must beat the "
                        "static fleet inside the p99 SLO), unattended "
                        "scale-up MTTR under the burst, the gateway-"
                        "kill-mid-drain / provision-failure-mid-scale-"
                        "up / supervisor-kill-mid-scale drills, and N "
                        "seeded elasticity campaigns checked against "
                        "the scale invariants (BENCH_autoscale.json)")
    parser.add_argument("--allocator", action="store_true",
                        help="run the train/serve co-scheduling "
                        "drills: the diurnal+burst trace on ONE "
                        "4-slice fleet (allocator lends troughs to "
                        "training, preempts on the surge through the "
                        "notice/ack/role-change protocol) vs two "
                        "static half-fleets — goodput AND training "
                        "steps must both win — plus the supervisor-"
                        "kill-mid-handover / never-acking-trainer / "
                        "tenant-flood drills and N seeded "
                        "co-scheduling campaigns checked against the "
                        "allocation + WFQ invariants "
                        "(BENCH_allocator.json)")
    parser.add_argument("--fleet", action="store_true",
                        help="run the federated-gateway drills: the "
                        "N=1 vs N=4 accepted-throughput scaling pair "
                        "on the same saturating keyed trace, the "
                        "streaming-TTFT drive (p99 first token vs the "
                        "non-streaming first byte over the same "
                        "arrivals), the replica-kill drill (partitions "
                        "reassigned, zero lost, journal adopted), and "
                        "N seeded fleet chaos campaigns checked "
                        "against the merged-shard + lease invariants "
                        "(BENCH_fleet.json)")
    parser.add_argument("--obs", action="store_true",
                        help="run the telemetry-overhead drills: the "
                        "gateway claim path and the REAL engine step "
                        "path with the obs/ plane on vs off (min-of-N "
                        "wall-clock; <5%% is the acceptance bar), plus "
                        "modeled per-request cost evidence "
                        "(BENCH_obs.json)")
    parser.add_argument("--check", action="store_true",
                        help="perf-regression gate: fail if the simulated "
                        "cold/warm makespan regressed >10%% vs the "
                        "committed baseline")
    parser.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                        metavar="FILE", help="baseline for --check "
                        "(default: the committed BENCH_provision.json)")
    parser.add_argument("--out", default=None, metavar="FILE",
                        help="also write the JSON document to FILE")
    args = parser.parse_args(argv)
    if args.check:
        ok, problems, current = run_check(args.baseline)
        for p in problems:
            print(f"REGRESSION: {p}", file=sys.stderr)
        if ok:
            print(
                "perf gate ok: cold "
                f"{current['dag']['wall_s']:.0f}s, warm "
                f"{current['warm']['warm_wall_s']:.0f}s within 10% of "
                f"{args.baseline}",
                file=sys.stderr,
            )
        return 0 if ok else 1
    if args.resilience:
        result = run_resilience_benchmark(args.slices)
    elif args.supervise:
        result = run_supervise_benchmark(args.slices)
    elif args.elastic:
        result = run_elastic_benchmark(args.slices)
    elif args.fleetscale:
        result = run_fleetscale_benchmark()
    elif args.chaos:
        result = run_chaos_benchmark(campaigns=max(1, args.campaigns))
    elif args.serve:
        result = run_serve_benchmark(args.slices)
    elif args.serve_chaos:
        result = run_serve_chaos_benchmark(campaigns=max(1, args.campaigns))
    elif args.fleet:
        result = run_fleet_benchmark(campaigns=max(1, args.campaigns))
    elif args.autoscale:
        result = run_autoscale_benchmark(campaigns=max(1, args.campaigns))
    elif args.allocator:
        result = run_allocator_benchmark(campaigns=max(1, args.campaigns))
    elif args.obs:
        result = run_obs_overhead_benchmark()
    elif args.warm:
        result = {
            "benchmark": "provision_warm",
            "metric": "warm_over_cold_makespan",
            "unit": "fraction (target <= 0.10)",
            "num_slices": args.slices,
            "model_seconds": dict(SIM_SECONDS),
            **run_warm_drill(args.slices),
        }
        result["value"] = result["warm_ratio"]
    else:
        result = run_benchmark(args.slices)
    doc = json.dumps(result, indent=2, sort_keys=True)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    if args.resilience:
        crash = result["crash_resume"]
        print(
            f"\n{args.slices}-slice resilience (simulated): SIGKILL at "
            f"{crash['kill_at']} -> resume redid "
            f"{crash['resumed_tasks']}/{crash['cold_tasks']} tasks "
            f"({crash['redo_ratio']:.1%} of cold work, MTTR "
            f"{crash['mttr_wall_s']:.0f}s); slice-loss heal scoped="
            f"{result['slice_loss']['scoped_to_lost_slice_only']} "
            f"healthy-untouched="
            f"{result['slice_loss']['healthy_tfstate_untouched']} "
            f"converge-runs={result['slice_loss']['ansible_runs']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.supervise:
        mttr = result["mttr"]
        breaker = result["breaker_drill"]
        print(
            f"\n{args.slices}-slice supervise (simulated): slice "
            f"{mttr['lost_slice']} preempted at t={mttr['preempt_at_s']:.0f}"
            f"s -> detected +{mttr['detect_s']:.0f}s, healed unattended in "
            f"{result['unattended_mttr_s']:.0f}s (manual baseline "
            f"{result['manual_mttr_s']:.0f}s + {mttr['interval_s']:.0f}s "
            f"interval = budget {result['mttr_budget_s']:.0f}s); breaker "
            f"storm: {breaker['heals_attempted']} attempts, "
            f"{breaker['rate_limited']} rate-limited, trips "
            f"{breaker['breaker_trips']}, ends "
            f"{breaker['end_verdict']} -> passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.elastic:
        drill = result["drill"]
        print(
            f"\n{args.slices}-slice elastic training (simulated): slice "
            f"{drill['lost_slice']} preempted at "
            f"t={drill['preempt_at_s']:.0f}s mid-step -> trainer lost "
            f"{result['steps_lost']} step(s) (<= "
            f"{result['checkpoint_every_steps']} per interval), resumed "
            f"training {result['value']:.0f}s after the preemption "
            f"(budget {result['budget_s']:.0f}s), ledger job MTTR "
            f"{result['ledger']['job_mttr_s']}s -> "
            f"passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.fleetscale:
        outage = result["outage"]
        ticks = result["ticks"]
        counts = sorted(int(n) for n in ticks)
        costs = " -> ".join(
            f"{n}: {ticks[str(n)]['steady_tick_cost_s']:.1f}s"
            for n in counts
        )
        print(
            f"\nfleet-scale supervise (simulated): steady tick cost "
            f"{costs} ({result['value']:.2f}x for "
            f"{result['fleet_growth_x']:.0f}x the fleet); zone outage "
            f"{outage['lost_slices']}/{outage['num_slices']} slices -> "
            f"{outage['heals_succeeded']} parallel scoped heals in "
            f"{outage['heal_makespan_s']:.0f}s "
            f"({outage['makespan_over_single_heal']:.1f}x one heal, "
            f"{outage['parallel_speedup_x']:.1f}x vs serial) -> "
            f"passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.chaos:
        blast = result["blast_radius"]
        sweep = result["campaigns"]
        print(
            f"\nchaos campaigns (simulated): blast radius "
            f"{blast['lost_slices']}/{blast['num_slices']} slices of "
            f"domain {blast['lost_domain']} -> breaker open only there="
            f"{blast['breaker_open_only_lost_domain']}, healthy-domain "
            f"heals flowed={blast['heals_flowed_in_healthy_domains']}, "
            f"canaries={blast['canary_heals']}, domain MTTR "
            f"{blast['blast_radius_mttr_s']:.0f}s; "
            f"{sweep['campaigns']} seeded campaigns: "
            f"{sweep['converged']} converged, "
            f"{sweep['violation_count']} invariant violation(s), MTTR "
            f"mean {sweep['mttr_mean_s']:.0f}s / max "
            f"{sweep['mttr_max_s']:.0f}s -> passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.obs:
        print(
            f"\ntelemetry overhead (best paired wall): claim "
            f"{result['claim']['overhead_pct']:+.1f}%, real engine "
            f"step {result['real_step']['overhead_pct']:+.1f}% "
            f"({result['real_step']['per_request_us']:.0f}us/request) "
            f"— gated <5%; modeled evidence: step "
            f"{result['modeled_step']['per_request_us']:.0f}us/request,"
            f" drive "
            f"{result['modeled_drive']['per_request_us']:.0f}us/request"
            f" -> passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.autoscale:
        el = result["elastic"]
        st = result["static"]
        sweep = result["campaigns"]
        drills = result["drills"]
        print(
            f"\nautoscale (simulated, diurnal+burst): elastic "
            f"{el['slice_hours_per_1k_tokens']} vs static "
            f"{st['slice_hours_per_1k_tokens']} slice-hr/1k tokens "
            f"({result['cost_savings_vs_static']:.1%} cheaper), p99 "
            f"{el['p99_latency_s']:.1f}s (SLO {result['slo_p99_s']:.0f}"
            f"s), scale-up MTTR {result['value']:.0f}s, "
            f"{el['scales']['done_down']} down / "
            f"{el['scales']['done_up']} up; drills: gw-kill-mid-drain "
            f"redone {drills['gateway_kill_mid_drain']['redone_after_kill']}"
            f", up-loss aborts "
            f"{drills['slice_loss_mid_scale_up']['scales']['aborted']}, "
            f"sup-kill restarts "
            f"{drills['supervisor_kill_mid_scale']['supervisor_restarts']}"
            f"; {sweep['campaigns']} campaigns: {sweep['converged']} "
            f"converged, {sweep['violation_count']} violation(s) -> "
            f"passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.allocator:
        good = result["goodput"]
        train = result["training"]
        sweep = result["campaigns"]
        drills = result["drills"]
        print(
            f"\nco-scheduling (simulated, diurnal+burst): ONE 4-slice "
            f"fleet completed {good['coscheduled_completed']} vs the "
            f"2-slice static half's {good['static_serve_completed']} "
            f"(+{good['margin']}), banked "
            f"{train['coscheduled_steps']} training steps vs the "
            f"static half's {train['static_train_steps']:.0f} "
            f"({train['coscheduled_steps_per_day']:.0f} vs "
            f"{train['static_steps_per_day']:.0f} steps/day); "
            f"preemption MTTR {result['value']:.0f}s (budget "
            f"{result['mttr_budget_s']:.0f}s), worst resume lost "
            f"{result['max_resume_steps_lost']} step(s) (<= "
            f"{result['checkpoint_every_steps']}/interval); drills: "
            f"kill-mid-handover restarts "
            f"{drills['supervisor_kill_mid_handover']['supervisor_restarts']}"
            f", never-ack forced "
            f"{drills['never_acking_trainer']['handovers']['forced']}, "
            f"tenant-flood sheds "
            f"{drills['tenant_flood']['sheds']}; "
            f"{sweep['campaigns']} campaigns: {sweep['converged']} "
            f"converged, {sweep['violation_count']} violation(s) -> "
            f"passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.serve_chaos:
        sweep = result["campaigns"]
        kill = result["kill_drill"]
        print(
            f"\nserve chaos (simulated): {sweep['campaigns']} seeded "
            f"supervisor+gateway campaigns: {sweep['converged']} "
            f"converged, {sweep['violation_count']} request-plane "
            f"violation(s) ({sweep['accepted']} accepted -> "
            f"{sweep['completed']} completed + {sweep['expired']} "
            f"expired, {sweep['requeues']} requeues, "
            f"{sweep['gateway_kills']} gateway kill(s)); kill drill: "
            f"{kill['inflight_at_kill']} in-flight at SIGKILL, "
            f"{kill['requests_redone']} redone, "
            f"{kill['requests_lost']} lost, "
            f"{kill['duplicates_replayed_from_journal']}/"
            f"{kill['duplicates_resubmitted']} duplicates answered "
            f"from the journal, restart-to-first-token "
            f"{kill['restart_to_first_token_s']}s -> "
            f"passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.fleet:
        sc = result["scaling"]
        st = result["streaming"]
        sweep = result["campaigns"]
        kill = result["kill_drill"]
        print(
            f"\ngateway fleet (simulated): accepted throughput "
            f"{sc['n1']['accepted_per_sec']:.1f} req/s (N=1) -> "
            f"{sc['n4']['accepted_per_sec']:.1f} req/s (N=4) = "
            f"{result['value']:.2f}x (bar 2.5x); streaming TTFT p50 "
            f"{st['ttft_p50_s']:.2f}s / p99 {st['ttft_p99_s']:.2f}s vs "
            f"non-streaming first byte p99 "
            f"{st['full_response_p99_s']:.2f}s "
            f"({st['streamed_chunks']} chunks, {st['sessions']} "
            f"sessions); kill drill: {kill['partitions_reassigned']} "
            f"partition(s) -> {kill['successor']}, "
            f"{kill['requests_redone']} redone, "
            f"{kill['requests_lost']} lost, MTTR "
            f"{kill['kill_to_reassign_s']}s (budget "
            f"{result['mttr_budget_s']:.0f}s); {sweep['campaigns']} "
            f"campaigns: {sweep['converged']} converged, "
            f"{sweep['violation_count']} violation(s), "
            f"{sweep['lease_fenced_pulls']} fenced pull(s) -> "
            f"passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.serve:
        rat = result["request_at_a_time"]
        cont = result["continuous"]
        outage = result["outage"]
        breaker = result["breaker"]
        print(
            f"\n{args.slices}-slice serving gateway (simulated, open-"
            f"loop): request-at-a-time {rat['tokens_per_sec']:.0f} tok/s "
            f"(p99 {rat['p99_latency_s']:.1f}s) -> continuous batching "
            f"{cont['tokens_per_sec']:.0f} tok/s "
            f"({result['value']:.2f}x, p99 {cont['p99_latency_s']:.1f}s, "
            f"{cont['tokens_per_sec_per_chip']:.1f} tok/s/chip); slice "
            f"outage at t={outage['outage']['at']:.0f}s: "
            f"{outage['requeued_after_slice_loss']} in-flight requeued, "
            f"{outage['sheds']} shed(s) all inside the demand window, "
            f"goodput {outage['goodput_over_nominal']:.0%} of nominal, "
            f"p99 {outage['p99_latency_s']:.1f}s; breaker hold: "
            f"{breaker['breaker_rejects']} refused, "
            f"{breaker['admitted_during_hold']} admitted; "
            f"shared-prefix warm "
            f"{result['shared_prefix']['warm']['tokens_per_sec_per_chip']:.1f}"
            f" tok/s/chip = {result['shared_prefix']['value']:.2f}x PR-9 "
            f"(hit rate "
            f"{result['shared_prefix']['warm']['engine']['prefix']['hit_rate']:.0%}"
            f", shared prefix re-prefilled "
            f"{result['shared_prefix']['warm']['engine']['shared_prefix_reprefilled_on_hits']}"
            f" tok on hits); paged slots: peak busy "
            f"{result['paged_slots']['value']} vs fixed "
            f"{result['paged_slots']['fixed_peak_slots_busy']} "
            f"(memory-equal); speculative k=4: "
            f"{result['speculative']['spec']['tokens_per_sec_per_chip']:.1f}"
            f" tok/s/chip = {result['speculative']['value']:.2f}x the "
            f"paged baseline at matched memory (acceptance "
            f"{result['speculative']['acceptance_rate']:.0%}, p99 "
            f"{result['speculative']['spec']['p99_latency_s']:.1f}s vs "
            f"{result['speculative']['baseline']['p99_latency_s']:.1f}s)"
            f" -> passes={result['passes']}",
            file=sys.stderr,
        )
        return 0 if result["passes"] else 1
    if args.warm:
        print(
            f"\n{args.slices}-slice warm re-provision (simulated): cold "
            f"{result['cold_wall_s']:.0f}s -> warm "
            f"{result['warm_wall_s']:.0f}s "
            f"({result['warm_ratio']:.1%}; "
            f"{result['warm_tasks_executed']} tasks executed, "
            f"{result['warm_converge_tasks_executed']} converges)",
            file=sys.stderr,
        )
        return 0 if result["warm_ratio"] <= 0.10 else 1
    print(
        f"\n{args.slices}-slice provision (simulated): "
        f"sequential {result['sequential']['wall_s']:.0f}s -> "
        f"barrier DAG {result['barrier_dag']['wall_s']:.0f}s -> "
        f"pipelined {result['dag']['wall_s']:.0f}s "
        f"({result['value']:.2f}x vs sequential, "
        f"{result['pipeline_vs_barrier']:.2f}x vs the barrier; warm "
        f"re-run {result['warm']['warm_wall_s']:.0f}s = "
        f"{result['warm']['warm_ratio']:.1%} of cold; critical path "
        f"{' -> '.join(result['critical_path'])})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
