#!/usr/bin/env bash
#
# TPU-native cluster provisioning — same two-command UX contract as the
# reference (reference setup.sh:8-12): `./setup.sh` provisions,
# `./setup.sh -c` destroys. The wizard/orchestration engine that the
# reference kept in 551 lines of bash lives in the tested Python package;
# this entrypoint only dispatches.

set -o errexit -o pipefail

cd "$(dirname "$0")"
exec python3 -m tritonk8ssupervisor_tpu.cli.main --workdir "$PWD" "$@"
